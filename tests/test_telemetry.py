"""Fleet telemetry plane (ISSUE 14; docs/OBSERVABILITY.md "The telemetry
plane").

Layers, smallest first:

- store units: bounded rings, reset-safe counter rates (a worker respawn
  must never read as a negative rate), histogram window-delta quantiles;
- SLO units: objective interpolation, the two-window ok/pending/firing
  machine over synthetic history;
- fleet-merge units: counters summed, gauges proc-labeled, histograms
  merged bucket-wise EXACTLY, stale sources marked and never fatal;
- config: [telemetry] / [model.slo] TOML + validation + dot overrides;
- HTTP e2e on a real toy server: /metrics content negotiation + # EOF
  (ISSUE 14 satellite), /stats/history, /alerts alert lifecycle,
  /debug/profile, the /stats telemetry/utilization blocks, and the
  sampler thread's clean shutdown on drain.
"""

import asyncio
import io
import json
import threading
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.config import (ModelConfig, ServerConfig, SloConfig,
                             TelemetryConfig, load_config)
from tpuserve.obs import Metrics
from tpuserve.server import ServerState, make_app
from tpuserve.telemetry import merge_expositions, parse_exposition
from tpuserve.telemetry.fleet import sum_counter
from tpuserve.telemetry.slo import SloEngine, UtilizationDeriver, good_fraction
from tpuserve.telemetry.store import (MetricSampler, TimeSeriesStore,
                                      quantile_from_counts)

# ---------------------------------------------------------------------------
# Time-series store
# ---------------------------------------------------------------------------


def test_rings_are_bounded():
    m = Metrics(16)
    c = m.counter("x_total")
    store = TimeSeriesStore(m, capacity=8)
    for i in range(50):
        c.inc()
        store.sample(now=1000.0 + i)
    h = store.history("x_total")
    assert len(h["t"]) == 8  # deque maxlen: newest kept
    assert h["v"][-1] == 50.0 and h["v"][0] == 43.0


def test_counter_rate_handles_resets_without_negative_rates():
    """A respawned process's counter restarts at 0 — the increase across
    the reset is the NEW value, and no derived rate is ever negative."""
    m = Metrics(16)
    c = m.counter("req_total")
    store = TimeSeriesStore(m, capacity=32)
    values = [10.0, 20.0, 30.0, 3.0, 6.0]  # reset between 30 -> 3
    for i, v in enumerate(values):
        c.value = v
        store.sample(now=100.0 + i)
    h = store.history("req_total")
    assert all(r >= 0 for r in h["rate_per_s"])
    # 10 + 10 + (reset: 3) + 3 of genuine increase
    assert h["increase"] == pytest.approx(10 + 10 + 3 + 3)
    assert store.counter_increase("req_total") == pytest.approx(26.0)


def test_counter_window_selects_left_edge_sample():
    m = Metrics(16)
    c = m.counter("w_total")
    store = TimeSeriesStore(m, capacity=32)
    t0 = time.time()
    for i in range(10):
        c.value = float(i)
        store.sample(now=t0 - 9 + i)  # one sample per second, ending now
    inc = store.counter_increase("w_total", window_s=3.0)
    # window covers the last ~3 s of samples plus the left-edge sample
    assert 3.0 <= inc <= 4.0


def test_histogram_window_delta_and_quantiles():
    m = Metrics(16)
    h = m.histogram("lat_ms{model=t,phase=total}")
    store = TimeSeriesStore(m, capacity=32)
    store.sample(now=time.time() - 1.0)
    for _ in range(100):
        h.observe(5.0)
    for _ in range(10):
        h.observe(500.0)
    store.sample(now=time.time())
    out = store.history("lat_ms{model=t,phase=total}")
    assert out["kind"] == "histogram"
    d = out["delta"]
    assert d["n"] == 110
    assert d["p50_ms"] < 10.0
    assert d["p99_ms"] > 100.0
    # the delta ignores anything observed before the first sample
    reset = store.histogram_delta("lat_ms{model=t,phase=total}")
    assert reset["n"] == 110


def test_histogram_delta_survives_reset():
    m = Metrics(16)
    h = m.histogram("r_ms{model=t}")
    store = TimeSeriesStore(m, capacity=32)
    for _ in range(5):
        h.observe(1.0)
    store.sample(now=200.0)
    # simulate a respawned process: fresh histogram under the same name
    with m._lock:
        m._histograms.clear()
    h2 = m.histogram("r_ms{model=t}")
    h2.observe(2.0)
    store.sample(now=201.0)
    d = store.histogram_delta("r_ms{model=t}")
    assert d["n"] == 1  # the reset contributes its new counts, not -4
    assert all(c >= 0 for c in d["counts"])


def test_quantile_from_counts_empty_and_overflow():
    assert quantile_from_counts([1.0, 2.0], [0, 0, 0], 0.5) is None
    assert quantile_from_counts([1.0, 2.0], [0, 0, 5], 0.99) == float("inf")


def test_match_by_base_name():
    m = Metrics(16)
    m.counter("req_total{model=a}")
    m.counter("req_total{model=b}")
    m.counter("other_total")
    store = TimeSeriesStore(m, capacity=4)
    store.sample()
    assert sorted(store.match("req_total")) == [
        "req_total{model=a}", "req_total{model=b}"]
    assert store.match("req_total{model=a}") == ["req_total{model=a}"]
    assert store.match("nope") == []


def test_sampler_thread_stops_cleanly():
    """The sampler correctness satellite's shutdown half: stop() joins the
    thread promptly and is idempotent."""
    m = Metrics(16)
    m.counter("x_total")
    store = TimeSeriesStore(m, capacity=8)
    s = MetricSampler(store, 0.02)
    s.start()
    deadline = time.time() + 5.0
    while store.samples_total < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert store.samples_total >= 3
    s.stop()
    assert not s.is_alive()
    s.stop()  # idempotent
    # no stray telemetry thread left behind
    assert all("tpuserve-telemetry" != t.name
               for t in threading.enumerate())


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


def test_good_fraction_interpolates_inside_bucket():
    bounds = [10.0, 20.0, 30.0]
    # 10 requests in (10, 20] bucket; objective mid-bucket at 15 -> half
    counts = [0, 10, 0, 0]
    assert good_fraction(bounds, counts, 15.0) == pytest.approx(0.5)
    assert good_fraction(bounds, counts, 20.0) == pytest.approx(1.0)
    assert good_fraction(bounds, counts, 9.0) == pytest.approx(0.0)
    assert good_fraction(bounds, [0, 0, 0, 0], 15.0) is None


def _slo_rig(windows=(0.5, 1.0, 30.0), latency_ms=20.0, burn_alert=10.0):
    m = Metrics(16)
    store = TimeSeriesStore(m, capacity=64)
    eng = SloEngine(m, store, list(windows))
    assert eng.register("toy", SloConfig(latency_ms=latency_ms,
                                         availability=0.999,
                                         burn_alert=burn_alert))
    h = m.histogram("latency_ms{model=toy,phase=total}")
    return m, store, eng, h


def test_slo_disabled_model_not_registered():
    m = Metrics(16)
    eng = SloEngine(m, TimeSeriesStore(m, 8), [1.0, 2.0])
    assert not eng.register("off", SloConfig())  # latency_ms = 0
    assert eng.state_of("off") == "ok"
    assert eng.alerts()["models"] == {}


def test_burn_fires_and_clears():
    """The two-window machine: all-bad traffic fires (burn ~1000 over
    budget 0.001), and once the bad window ages out the alert returns to
    ok — fast to fire, fast to clear."""
    m, store, eng, h = _slo_rig(windows=(0.4, 0.8, 30.0))
    store.sample()
    for _ in range(50):
        h.observe(500.0)  # objective is 20 ms: every one bad
    store.sample()
    eng.tick()
    assert eng.state_of("toy") == "firing"
    alerts = eng.alerts()
    assert alerts["status"] == "firing"
    row = alerts["models"]["toy"]
    assert row["burn"]["0.4s"] > 100
    assert m.gauge("slo_alert_state{model=toy}").value == 2.0
    # good traffic + the bad samples aging past the windows -> ok
    time.sleep(1.0)
    for _ in range(50):
        h.observe(1.0)
    store.sample()
    eng.tick()
    assert eng.state_of("toy") == "ok", eng.alerts()
    assert m.gauge("slo_alert_state{model=toy}").value == 0.0
    # burn gauges exist per window
    assert "slo_burn_rate{model=toy,window=0.4s}" in m._gauges


def test_burn_pending_on_short_window_only():
    """Bad traffic only inside the short window (the mid window still
    mostly good) -> pending, not firing."""
    m, store, eng, h = _slo_rig(windows=(0.4, 30.0, 60.0))
    store.sample()
    for _ in range(1000):
        h.observe(1.0)  # long-window history: good
    store.sample()
    time.sleep(0.5)
    for _ in range(5):
        h.observe(500.0)
    store.sample()
    eng.tick()
    # short window: 5/5 bad -> burn 1000; mid window: 5/1005 bad -> ~5
    assert eng.state_of("toy") == "pending", eng.alerts()


def test_no_evidence_holds_ok():
    m, store, eng, h = _slo_rig()
    eng.tick()  # zero samples: no deltas anywhere
    assert eng.state_of("toy") == "ok"
    assert all(b is None for b in eng.burn_rates("toy").values())


# ---------------------------------------------------------------------------
# Utilization derivation
# ---------------------------------------------------------------------------


def test_utilization_from_device_seconds_rate():
    m = Metrics(16)
    store = TimeSeriesStore(m, capacity=32)
    util = UtilizationDeriver(m, store, window_s=10.0)
    c0 = m.device_seconds_counter("toy", 0)
    c1 = m.device_seconds_counter("toy", 1)
    t0 = time.time() - 4.0
    for i in range(5):
        c0.value = 0.9 * i   # ~90% busy chip
        c1.value = 0.1 * i   # ~10% busy chip
        store.sample(now=t0 + i)
    util.tick()
    g0 = m.gauge("device_utilization{model=toy,replica=0}")
    g1 = m.gauge("device_utilization{model=toy,replica=1}")
    assert g0.value == pytest.approx(0.9, abs=0.05)
    assert g1.value == pytest.approx(0.1, abs=0.05)
    stats = util.stats()
    assert stats["toy"]["device_seconds_total"] == pytest.approx(4.0)
    assert stats["toy"]["mean_utilization"] == pytest.approx(0.5, abs=0.05)


def test_bench_utilization_and_burn_helpers():
    import bench

    block = bench.utilization_block({0: 1.0, 1: 0.0},
                                    {0: 9.0, 1: 4.0}, wall_s=10.0, n_chips=2)
    assert block["per_replica"] == {"0": 0.8, "1": 0.4}
    assert block["mean_utilization"] == pytest.approx(0.6)
    assert block["device_seconds"] == pytest.approx(12.0)

    m = Metrics(16)
    h = m.histogram("latency_ms{model=resnet50,phase=total}")
    before = h.snapshot()
    for _ in range(99):
        h.observe(1.0)
    h.observe(10_000.0)
    burn = bench.burn_from_snapshots(h.bounds, before, h.snapshot(),
                                     objective_ms=100.0, availability=0.999)
    assert burn == pytest.approx(10.0, rel=0.05)  # 1% bad / 0.1% budget


# ---------------------------------------------------------------------------
# Fleet merge
# ---------------------------------------------------------------------------


def _registry(reqs: int, lat_ms: list, depth: float) -> str:
    m = Metrics(16)
    c = m.counter("requests_total{model=toy}")
    c.inc(reqs)
    h = m.histogram("latency_ms{model=toy,phase=total}")
    for v in lat_ms:
        h.observe(v, trace_id="ab" * 16)  # exemplars must not break parse
    m.gauge("queue_depth{model=toy}").set(depth)
    return m.render_prometheus()


def test_merge_sums_counters_exactly():
    a = _registry(7, [1.0], 2.0)
    b = _registry(35, [2.0], 3.0)
    merged = merge_expositions([("worker0", a), ("worker1", b)])
    assert sum_counter(merged, "requests_total",
                       'model="toy"') == pytest.approx(42.0)
    # exact equality against the per-source sum — the smoke's gate
    per_source = sum_counter(a, "requests_total") + \
        sum_counter(b, "requests_total")
    assert sum_counter(merged, "requests_total") == per_source


def test_merge_labels_gauges_per_process():
    merged = merge_expositions([("worker0", _registry(1, [], 2.0)),
                                ("worker1", _registry(1, [], 5.0))])
    samples = parse_exposition(merged)["samples"]
    depths = {ls: v for b, ls, v in samples if b == "queue_depth"}
    assert depths == {'model="toy",proc="worker0"': 2.0,
                      'model="toy",proc="worker1"': 5.0}


def test_merge_histograms_bucketwise_exact():
    a = _registry(0, [1.0, 1.0, 50.0], 0)
    b = _registry(0, [1.0, 500.0], 0)
    merged = merge_expositions([("w0", a), ("w1", b)])
    parsed = parse_exposition(merged)
    assert parsed["types"]["latency_ms"] == "histogram"
    count = [v for base, ls, v in parsed["samples"]
             if base == "latency_ms_count"]
    assert count == [5.0]
    # every bucket's merged cumulative count == the sum of the sources'
    def buckets(text):
        return {ls: v for base, ls, v in parse_exposition(text)["samples"]
                if base == "latency_ms_bucket"}
    ba, bb, bm = buckets(a), buckets(b), buckets(merged)
    for ls, v in bm.items():
        assert v == ba.get(ls, 0.0) + bb.get(ls, 0.0), ls


def test_merge_marks_stale_sources_never_raises():
    merged = merge_expositions([("worker0", _registry(3, [1.0], 1.0)),
                                ("worker1", None), ("router1", None)])
    assert 'fleet_source_up{proc="worker0"} 1' in merged
    assert 'fleet_source_up{proc="worker1"} 0' in merged
    assert "# STALE worker1" in merged and "# STALE router1" in merged
    assert merged.rstrip().endswith("# EOF")
    # the live source's data still merged
    assert sum_counter(merged, "requests_total") == 3.0


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


def test_telemetry_and_slo_toml(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text("""
[telemetry]
sample_interval_s = 0.5
history_s = 60.0
burn_windows_s = [2.0, 5.0, 30.0]

[[model]]
name = "toy"
family = "toy"

[model.slo]
latency_ms = 50.0
availability = 0.99
burn_alert = 5.0
""")
    cfg = load_config(str(p))
    assert cfg.telemetry.sample_interval_s == 0.5
    assert cfg.telemetry.burn_windows_s == [2.0, 5.0, 30.0]
    assert cfg.models[0].slo.latency_ms == 50.0
    assert cfg.models[0].slo.availability == 0.99
    cfg2 = load_config(str(p), overrides=["model.toy.slo.latency_ms=75.0",
                                          "telemetry.sample_interval_s=0.1"])
    assert cfg2.models[0].slo.latency_ms == 75.0
    assert cfg2.telemetry.sample_interval_s == 0.1


def test_telemetry_config_validation():
    with pytest.raises(ValueError, match="sample_interval_s"):
        TelemetryConfig(sample_interval_s=0.0)
    with pytest.raises(ValueError, match="burn_windows_s"):
        TelemetryConfig(burn_windows_s=[60.0])  # needs >= 2 windows
    with pytest.raises(ValueError, match="burn_windows_s"):
        TelemetryConfig(burn_windows_s=[300.0, 60.0])  # must ascend
    with pytest.raises(ValueError, match="availability"):
        SloConfig(latency_ms=10.0, availability=1.0)
    with pytest.raises(ValueError, match="burn_alert"):
        SloConfig(latency_ms=10.0, burn_alert=0.0)
    with pytest.raises(ValueError, match="latency_ms"):
        SloConfig(latency_ms=-1.0)


# ---------------------------------------------------------------------------
# HTTP e2e (real toy server, manual sampler ticks for determinism)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def client(loop):
    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single",
                            request_timeout_ms=10_000.0, wire_size=8,
                            slo=SloConfig(latency_ms=20.0,
                                          availability=0.999))],
        decode_threads=2,
        telemetry=TelemetryConfig(sample_interval_s=30.0,  # manual ticks
                                  burn_windows_s=[0.5, 1.0, 30.0]),
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def setup():
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    c = loop.run_until_complete(setup())
    yield lambda coro: loop.run_until_complete(coro), c, state
    loop.run_until_complete(c.close())


def npy_bytes(seed: int = 0) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (8, 8, 3), dtype=np.uint8))
    return buf.getvalue()


NPY = "application/x-npy"


def test_metrics_content_negotiation_and_eof(client):
    """ISSUE 14 satellite: /metrics ends with `# EOF` and negotiates the
    OpenMetrics content type from Accept."""
    run, c, state = client

    async def go():
        async with c.get("/metrics") as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            body = await r.text()
        assert body.rstrip().endswith("# EOF")
        accept = ("application/openmetrics-text; version=1.0.0,"
                  "text/plain;q=0.5")
        async with c.get("/metrics", headers={"Accept": accept}) as r:
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text; version=1.0.0")
            body_om = await r.text()
        assert body_om.rstrip().endswith("# EOF")

    run(go())


def test_history_endpoint(client):
    run, c, state = client

    async def go():
        # bracket some traffic between two sampler ticks so the window
        # DELTA (not just the lifetime counts) has something in it
        state.sampler.tick()
        for i in range(4):
            async with c.post("/v1/models/toy:classify", data=npy_bytes(i),
                              headers={"Content-Type": NPY}) as r:
                assert r.status == 200
        state.sampler.tick()
        async with c.get("/stats/history") as r:
            inv = await r.json()
            assert r.status == 200
        assert "requests_total{model=toy}" in inv["metrics"]
        assert inv["samples_total"] >= 2
        async with c.get("/stats/history",
                         params={"metric": "requests_total"}) as r:
            data = await r.json()
            assert r.status == 200
        (series,) = data["series"]
        assert series["kind"] == "counter"
        assert len(series["t"]) >= 2
        assert "rate_per_s" in series and "increase" in series
        # histogram series carry the window-delta quantiles
        async with c.get(
                "/stats/history",
                params={"metric": "latency_ms{model=toy,phase=total}",
                        "window_s": "60"}) as r:
            data = await r.json()
            assert r.status == 200
        assert data["series"][0]["delta"]["n"] >= 1
        async with c.get("/stats/history",
                         params={"metric": "nope_total"}) as r:
            assert r.status == 404
        async with c.get("/stats/history",
                         params={"metric": "requests_total",
                                 "window_s": "-3"}) as r:
            assert r.status == 400

    run(go())


def test_alerts_lifecycle_over_http(client):
    """Bad latency inside the burn windows -> /alerts firing (and the
    slo_alert_state gauge follows); once the bad window ages out under
    good traffic -> ok."""
    run, c, state = client

    async def go():
        h = state.metrics.histogram("latency_ms{model=toy,phase=total}")
        state.sampler.tick()
        for _ in range(50):
            h.observe(500.0)  # objective 20 ms
        state.sampler.tick()
        async with c.get("/alerts") as r:
            alerts = await r.json()
            assert r.status == 200
        assert alerts["models"]["toy"]["state"] == "firing", alerts
        assert alerts["status"] == "firing"
        assert alerts["models"]["toy"]["burn"]["0.5s"] > 100
        # /stats mirrors the alert view + telemetry heartbeat
        async with c.get("/stats") as r:
            stats = await r.json()
        assert stats["slo"]["models"]["toy"]["state"] == "firing"
        assert stats["telemetry"]["samples_total"] >= 1
        await asyncio.sleep(1.2)  # bad samples age past the 1.0 s window
        for _ in range(20):
            h.observe(1.0)
        state.sampler.tick()
        await asyncio.sleep(0.05)
        state.sampler.tick()
        async with c.get("/alerts") as r:
            alerts = await r.json()
        assert alerts["models"]["toy"]["state"] == "ok", alerts

    run(go())


def test_utilization_gauges_after_traffic(client):
    run, c, state = client

    async def go():
        for i in range(6):
            async with c.post("/v1/models/toy:classify",
                              data=npy_bytes(100 + i),
                              headers={"Content-Type": NPY}) as r:
                assert r.status == 200
        state.sampler.tick()
        await asyncio.sleep(0.05)
        state.sampler.tick()
        async with c.get("/stats") as r:
            stats = await r.json()
        util = stats["utilization"]["toy"]
        assert "0" in util["per_replica"]
        assert util["device_seconds_total"] > 0
        # the gauge itself is on /metrics
        async with c.get("/metrics") as r:
            text = await r.text()
        assert "device_utilization{" in text
        assert "device_seconds_total{" in text

    run(go())


def test_profile_endpoint(client):
    run, c, state = client

    async def go():
        async with c.post("/debug/profile",
                          params={"duration_ms": "junk"}) as r:
            assert r.status == 400
        async with c.post("/debug/profile",
                          params={"duration_ms": "99999999"}) as r:
            assert r.status == 400
        async with c.post("/debug/profile",
                          params={"duration_ms": "150"}) as r:
            data = await r.json()
            assert r.status == 200, data
        assert isinstance(data["traceEvents"], list)
        meta = data["tpuserve_profile"]
        assert meta["duration_ms"] == 150.0
        assert meta["device_trace"]  # "ok" or an explicit unavailable note
        # one capture at a time: armed -> 409
        state.profiler._armed = True
        try:
            async with c.post("/debug/profile",
                              params={"duration_ms": "50"}) as r:
                assert r.status == 409
        finally:
            state.profiler._armed = False
        async with c.get("/stats") as r:
            stats = await r.json()
        assert stats["telemetry"]["profile"]["captures_total"] >= 1

    run(go())


def test_sampler_stops_on_drain():
    """The satellite's drain half: a real server's sampler thread joins
    during drain() — no orphan thread keeps ticking a dying registry."""
    loop = asyncio.new_event_loop()
    try:
        cfg = ServerConfig(
            models=[ModelConfig(name="toy", family="toy",
                                batch_buckets=[1], deadline_ms=2.0,
                                dtype="float32", num_classes=10,
                                parallelism="single", wire_size=8)],
            decode_threads=2, startup_canary=False,
            telemetry=TelemetryConfig(sample_interval_s=0.05),
        )
        state = ServerState(cfg)
        state.build()

        async def go():
            await state.start()
            assert state.sampler.is_alive()
            deadline = time.time() + 5.0
            while state.store.samples_total < 2 and time.time() < deadline:
                await asyncio.sleep(0.02)
            assert state.store.samples_total >= 2
            ok = await state.drain()
            assert ok
            assert not state.sampler.is_alive()
            await state.stop()  # idempotent sampler stop

        loop.run_until_complete(go())
    finally:
        loop.close()


def test_scheduler_slo_hook():
    """The shed-on-burn seam: a scheduler with an attached engine reads
    each model's live alert state; without one, everything is ok."""
    from tpuserve.config import SchedulerConfig
    from tpuserve.scheduler import FleetScheduler

    m = Metrics(16)
    sched = FleetScheduler(SchedulerConfig(enabled=True), m)
    assert sched.slo_state("toy") == "ok"
    store = TimeSeriesStore(m, 32)
    eng = SloEngine(m, store, [0.5, 1.0, 30.0])
    eng.register("toy", SloConfig(latency_ms=10.0))
    sched.slo = eng
    h = m.histogram("latency_ms{model=toy,phase=total}")
    store.sample()
    for _ in range(20):
        h.observe(400.0)
    store.sample()
    eng.tick()
    assert sched.slo_state("toy") == "firing"
