"""BERT family + text path (C3/C4, SURVEY.md §3d): tokenizer behavior,
(batch, seq) bucketing, seq-bucket/padding invariance, HTTP end-to-end.
VERDICT.md r2 item 3."""

import asyncio
import json

import numpy as np
import pytest

from tpuserve.config import ModelConfig
from tpuserve.models import build
from tpuserve.text import (
    CLS, PAD, SEP, UNK, WordPieceTokenizer, basic_tokenize, synthetic_vocab,
)

TINY = dict(layers=2, d_model=32, heads=2, d_ff=64, vocab_size=512)


def tiny_cfg(**over) -> ModelConfig:
    base = dict(
        name="bert", family="bert", batch_buckets=[1, 2],
        seq_buckets=[8, 16], deadline_ms=5.0, dtype="float32",
        num_classes=4, parallelism="single", request_timeout_ms=30_000.0,
        options=dict(TINY),
    )
    base.update(over)
    return ModelConfig(**base)


# -- tokenizer ----------------------------------------------------------------

def test_basic_tokenize():
    assert basic_tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    assert basic_tokenize("Café") == ["cafe"]  # accent stripped
    assert basic_tokenize("a中b") == ["a", "中", "b"]  # CJK isolated


def test_wordpiece_greedy_longest_match():
    vocab = {t: i for i, t in enumerate(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "un", "##aff", "##able", "##a", "##ff", "aff"])}
    tok = WordPieceTokenizer(vocab)
    assert tok.wordpiece("unaffable") == ["un", "##aff", "##able"]
    assert tok.wordpiece("zzz") == [UNK]


def test_encode_pads_and_masks():
    tok = WordPieceTokenizer(synthetic_vocab(2048))
    ids, mask = tok.encode("hello world", 16)
    assert ids.shape == (16,) and mask.shape == (16,)
    assert ids[0] == tok.cls_id
    n = int(mask.sum())
    assert ids[n - 1] == tok.sep_id
    assert np.all(ids[n:] == tok.pad_id) and np.all(mask[n:] == 0)


def test_encode_truncates():
    tok = WordPieceTokenizer(synthetic_vocab(2048))
    ids, mask = tok.encode("word " * 100, 8)
    assert ids.shape == (8,) and int(mask.sum()) == 8
    assert ids[-1] == tok.sep_id


def test_synthetic_vocab_deterministic_and_unkless():
    v1, v2 = synthetic_vocab(4096), synthetic_vocab(4096)
    assert v1 == v2
    tok = WordPieceTokenizer(v1)
    assert UNK not in tok.tokenize("arbitrary ascii text 123!")


def test_vocab_file_roundtrip(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
                            "hello", "##s"]))
    tok = WordPieceTokenizer.from_vocab_file(str(p))
    assert tok.tokenize("hellos") == ["hello", "##s"]


# -- model + bucketing --------------------------------------------------------

def test_full_size_matches_published_figures():
    """BERT-base with the standard 30,522-token vocab is ~110M params."""
    import jax
    import numpy as np

    from tpuserve.config import ModelConfig

    m = build(ModelConfig(name="b", family="bert", dtype="float32",
                          num_classes=2, options={"vocab_size": 30522}))
    p = jax.eval_shape(m.init_params, jax.random.key(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(p))
    assert 105e6 < n < 115e6, n


@pytest.fixture(scope="module")
def served():
    """Tiny BERT behind the real runtime (module-scoped: compiles 4 buckets)."""
    from tpuserve.runtime import build_runtime

    model = build(tiny_cfg())
    rt = build_runtime(model)
    return model, rt


@pytest.mark.slow
def test_buckets_cross_product(served):
    model, rt = served
    assert model.buckets() == [(1, 8), (1, 16), (2, 8), (2, 16)]
    assert sorted(rt.executables) == sorted(model.buckets())


def test_group_key_picks_seq_bucket(served):
    model, _ = served
    short = model.host_decode(b'{"text": "hi"}', "application/json")
    long = model.host_decode(
        json.dumps({"text": "many words " * 6}).encode(), "application/json")
    assert model.group_key(short) == 8
    assert model.group_key(long) == 16
    assert model.bucket_for(2, group=8) == (2, 8)
    assert model.bucket_for(3, group=16) == (2, 16)  # clamps to largest batch


def test_seq_bucket_invariance(served):
    """The same text produces the same logits in the 8- and 16-seq buckets:
    padded lanes and extra padded positions cannot leak into real lanes."""
    model, rt = served
    item = model.host_decode(b'{"text": "hello world"}', "application/json")
    out8 = rt.fetch(rt.run((1, 8), model.assemble([item], (1, 8))))
    out16 = rt.fetch(rt.run((1, 16), model.assemble([item], (1, 16))))
    np.testing.assert_allclose(out8["probs"], out16["probs"], atol=1e-5)
    np.testing.assert_array_equal(out8["indices"], out16["indices"])


def test_batch_padding_invariance(served):
    """A request's result is identical alone vs sharing a padded batch."""
    model, rt = served
    a = model.host_decode(b'{"text": "alpha beta"}', "application/json")
    b_ = model.host_decode(b'{"text": "gamma"}', "application/json")
    solo = rt.fetch(rt.run((1, 8), model.assemble([a], (1, 8))))
    pair = rt.fetch(rt.run((2, 8), model.assemble([a, b_], (2, 8))))
    np.testing.assert_allclose(solo["probs"][0], pair["probs"][0], atol=1e-5)


def test_text_plain_body(served):
    model, _ = served
    item = model.host_decode(b"raw text body", "text/plain")
    assert item.dtype == np.int32 and item.ndim == 1


def test_bad_json_raises(served):
    model, _ = served
    with pytest.raises(ValueError):
        model.host_decode(b'{"no_text": 1}', "application/json")


# -- sequence-parallel serving -----------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sequence_parallel_serving_matches_dense(impl):
    """attention=ring|ulysses + sp=2 on the sharded 8-device mesh:
    seq-sharded activations (K/V ppermute rotation vs head all-to-all),
    identical logits incl. a padded lane; the AOT-compiled path runs."""
    import jax

    from tpuserve.runtime import build_runtime

    sp_model = build(tiny_cfg(parallelism="sharded", sp=2, batch_buckets=[4],
                              seq_buckets=[16],
                              options={**TINY, "attention": impl}))
    rt = build_runtime(sp_model)  # binds the mesh + AOT-compiles SP forward
    dense = build(tiny_cfg(batch_buckets=[4], seq_buckets=[16]))

    items = [dense.host_decode(
        json.dumps({"text": f"sequence parallel serving {i}"}).encode(),
        "application/json") for i in range(3)]  # 3 of 4 lanes real
    batch = dense.assemble(items, (4, 16))
    params = dense.init_params(jax.random.key(0))  # same tree either impl
    # Same params: the runtime loaded its own; rerun the SP forward with
    # dense's params for the apples-to-apples check.
    out_sp = jax.jit(sp_model.forward)(params, batch)
    out_dense = jax.jit(dense.forward)(params, batch)
    np.testing.assert_allclose(np.asarray(out_sp["probs"]),
                               np.asarray(out_dense["probs"]), atol=1e-5)
    assert np.asarray(rt.run((4, 16), batch)["probs"]).shape == (4, 4)


def test_ulysses_rejects_indivisible_heads():
    with pytest.raises(ValueError, match="heads"):
        build(tiny_cfg(parallelism="sharded", sp=4, seq_buckets=[16],
                       options={**TINY, "attention": "ulysses", "heads": 2}))


def test_ring_requires_divisible_seq_buckets():
    with pytest.raises(ValueError, match="divisible"):
        build(tiny_cfg(parallelism="sharded", sp=4, seq_buckets=[8, 18],
                       options={**TINY, "attention": "ring"}))


def test_ring_rejects_replica_mode():
    with pytest.raises(ValueError, match="replica"):
        build(tiny_cfg(parallelism="replica",
                       options={**TINY, "attention": "ring"}))


def test_ring_without_bound_mesh_errors_clearly():
    import jax

    model = build(tiny_cfg(parallelism="sharded", sp=2, batch_buckets=[4],
                           seq_buckets=[16], options={**TINY, "attention": "ring"}))
    params = model.init_params(jax.random.key(0))
    batch = model.assemble([model.host_decode(b"hello", "text/plain")], (4, 16))
    with pytest.raises(ValueError, match="bind_mesh"):
        model.forward(params, batch)


def test_nonpositive_sp_rejected_at_config():
    with pytest.raises(ValueError, match="sp"):
        tiny_cfg(sp=0)


# -- HTTP end-to-end ----------------------------------------------------------

@pytest.mark.slow
def test_bert_http_end_to_end():
    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.config import ServerConfig
    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(models=[tiny_cfg()], decode_threads=2)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.post(
                "/v1/models/bert:classify",
                data=json.dumps({"text": "serve this text please"}).encode(),
                headers={"Content-Type": "application/json"})
            assert resp.status == 200, await resp.text()
            body = await resp.json()
            assert len(body["top_k"]) == 4
            assert abs(sum(e["prob"] for e in body["top_k"]) - 1.0) < 1e-3

            # per-(batch, seq) executables are visible in the inventory
            resp = await client.get("/v1/models")
            inv = await resp.json()
            assert inv["bert"]["buckets"] == [[1, 8], [1, 16], [2, 8], [2, 16]]

            # malformed JSON -> 400
            resp = await client.post(
                "/v1/models/bert:classify", data=b"{oops",
                headers={"Content-Type": "application/json"})
            assert resp.status == 400

            # {"texts": [...]} client batch -> {"results": [...]} in order
            resp = await client.post(
                "/v1/models/bert:classify",
                data=json.dumps({"texts": ["first text", "second one"]}).encode(),
                headers={"Content-Type": "application/json"})
            assert resp.status == 200, await resp.text()
            body = await resp.json()
            assert len(body["results"]) == 2
            solo = await client.post(
                "/v1/models/bert:classify",
                data=json.dumps({"text": "second one"}).encode(),
                headers={"Content-Type": "application/json"})
            assert (await solo.json()) == body["results"][1]

            # non-string entries -> 400
            resp = await client.post(
                "/v1/models/bert:classify",
                data=json.dumps({"texts": ["ok", 7]}).encode(),
                headers={"Content-Type": "application/json"})
            assert resp.status == 400
        finally:
            await client.close()

    loop.run_until_complete(go())
    loop.close()
