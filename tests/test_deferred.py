"""Deferred-readback pool (tpuserve.deferred): epoch rotation, worker-death
containment, clean shutdown, config guardrails, HTTP serving from a TOML
config. SURVEY.md §4-1/§4-2; VERDICT.md r2 item 5.

Workers run as spawned subprocesses on the CPU backend (the test process has
a live XLA backend, so the pool picks spawn) — slow to fork (~seconds each),
so the pool fixtures keep worker counts and epochs small.
"""

import asyncio
import io

import numpy as np
import pytest

from tpuserve.config import ModelConfig, load_config
from tpuserve.deferred import DeferredPool
from tpuserve.models import build

pytestmark = pytest.mark.slow


def make_cfg(**over) -> ModelConfig:
    base = dict(
        name="toy", family="toy", batch_buckets=[2, 4], deadline_ms=10.0,
        dtype="float32", num_classes=10, parallelism="single",
        session_mode="recycle", relay_workers=2, relay_slots=2,
        relay_epoch_images=8, relay_epoch_ms=400.0,
        request_timeout_ms=30_000.0,
    )
    base.update(over)
    return ModelConfig(**base)


def batch(n: int, seed: int | None = None) -> np.ndarray:
    """n-row toy batch; n must match the bucket it is enqueued under (shm
    slots are sized for the largest configured bucket — r4's replenish test
    passed `batch(i)` with i up to 5 into a (4,)-slot and blamed the
    resulting overflow ValueError on a readback race)."""
    rng = np.random.default_rng(n if seed is None else seed)
    return rng.integers(0, 255, (n, 8, 8, 3), dtype=np.uint8)


@pytest.fixture(scope="module")
def pool_env():
    """One prewarmed 2-worker pool + its event loop, shared by the module
    (spawn cost); tests that kill workers run last via ordering below."""
    cfg = make_cfg()
    model = build(cfg)
    pool = DeferredPool(cfg, "", model)
    pool.prewarm()
    loop = asyncio.new_event_loop()
    loop.run_until_complete(pool.start())
    yield loop, pool
    loop.run_until_complete(pool.stop())
    loop.close()


def test_timeout_floor_applied():
    cfg = make_cfg(request_timeout_ms=100.0, relay_epoch_ms=200.0)
    DeferredPool(cfg, "", build(cfg))
    assert cfg.request_timeout_ms == pytest.approx(2 * 200.0 + 1000.0)


def test_epoch_rotation_and_results(pool_env):
    loop, pool = pool_env

    async def go():
        # 3 batches of 4 rows: rows 0-7 fill worker A's 8-row epoch budget;
        # batch 3 forces rotation to worker B. All resolve with real results.
        futs = [await pool.enqueue((4,), batch(4)) for _ in range(3)]
        outs = await asyncio.wait_for(asyncio.gather(*futs), timeout=30)
        for out in outs:
            assert out["probs"].shape == (4, 3)
            assert np.all(out["probs"][:, 0] >= out["probs"][:, 1])
        assert pool.stats["epochs"] >= 1
        assert pool.stats["rows_total"] == 12

    loop.run_until_complete(go())


def test_epoch_deadline_fires_without_fill(pool_env):
    loop, pool = pool_env

    async def go():
        # One small batch, epoch far from full: the relay_epoch_ms timer must
        # retire the worker and resolve the future anyway.
        fut = await pool.enqueue((2,), batch(2))
        out = await asyncio.wait_for(fut, timeout=30)
        assert out["indices"].shape == (2, 3)

    loop.run_until_complete(go())


def test_worker_death_contained(pool_env):
    loop, pool = pool_env

    async def go():
        fut = await pool.enqueue((2,), batch(2))
        w = pool._active
        assert w is not None
        w.proc.kill()  # simulate OOM/preemption mid-epoch
        with pytest.raises(RuntimeError, match="died"):
            await asyncio.wait_for(fut, timeout=30)
        # The pool recovers: the next enqueue lands on a fresh worker.
        fut2 = await pool.enqueue((2,), batch(2))
        out = await asyncio.wait_for(fut2, timeout=120)
        assert out["indices"].shape == (2, 3)

    loop.run_until_complete(go())


def test_clean_shutdown_resolves_pending():
    """stop() must wait for the epoch readback: pending futures resolve with
    results, not 'worker died' (the r2 judge-observed 50 ms strand)."""
    cfg = make_cfg(relay_workers=2, relay_epoch_ms=5_000.0)
    pool = DeferredPool(cfg, "", build(cfg))
    pool.prewarm()
    loop = asyncio.new_event_loop()

    async def go():
        await pool.start()
        fut = await pool.enqueue((2,), batch(2))
        await pool.stop()  # epoch nowhere near done: stop retires + waits
        assert fut.done() and fut.exception() is None
        out = fut.result()
        assert out["indices"].shape == (2, 3)

    loop.run_until_complete(go())
    loop.close()


def test_recycle_serves_over_http_from_toml(tmp_path):
    """Recycle mode is launchable from a TOML config and serves end-to-end."""
    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.server import ServerState, make_app

    toml = tmp_path / "recycle.toml"
    toml.write_text(
        """
        decode_threads = 2
        startup_canary = false

        [[model]]
        name = "toy"
        family = "toy"
        batch_buckets = [2]
        deadline_ms = 5.0
        dtype = "float32"
        num_classes = 10
        parallelism = "single"
        session_mode = "recycle"
        relay_workers = 2
        relay_slots = 2
        relay_epoch_images = 4
        relay_epoch_ms = 300.0
        """
    )
    cfg = load_config(str(toml))
    assert cfg.models[0].session_mode == "recycle"
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            buf = io.BytesIO()
            np.save(buf, batch(1)[0])
            resp = await client.post(
                "/v1/models/toy:predict", data=buf.getvalue(),
                headers={"Content-Type": "application/x-npy"})
            assert resp.status == 200, await resp.text()
            body = await resp.json()
            assert len(body["top_k"]) == 3
        finally:
            await client.close()

    loop.run_until_complete(go())
    loop.close()


def test_pinned_shm_defers_unlink_past_inflight_write():
    """_PinnedShm: close() during an in-flight write must NOT invalidate the
    buffer; the unlink happens at unpin, and later pins are refused
    (VERDICT r4 weak 1 — the write-after-close ValueError)."""
    import threading
    import time
    from multiprocessing import shared_memory

    from tpuserve.deferred import _PinnedShm

    shm = _PinnedShm(1 << 20)
    name = shm.name
    errors: list[BaseException] = []
    copy_started = threading.Event()

    def writer():
        try:
            assert shm.pin()
            copy_started.set()
            # Simulate the multi-MB memcpy: touch the buffer repeatedly for a
            # while; with close() landing mid-loop this raised before the fix.
            view = np.frombuffer(shm.buf, dtype=np.uint8, count=1 << 20)
            for _ in range(50):
                view[:] = 7
                time.sleep(0.002)
            del view
            shm.unpin()
        except BaseException as e:  # noqa: BLE001 — reported to the test
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    copy_started.wait(5)
    shm.close()  # epoch readback path closes mid-copy
    # Segment must still be attachable while the write is in flight.
    assert not errors
    t.join(10)
    assert not errors, errors
    # After the last unpin the deferred unlink has happened...
    with pytest.raises(FileNotFoundError):
        shared_memory.SharedMemory(name=name)
    # ...and new writes are refused rather than crashing.
    assert shm.pin() is False


def test_results_during_slot_copy_reroutes_batch(monkeypatch):
    """Force the r4 judge-observed interleave deterministically: the epoch
    deadline retires the active worker and its results (→ w.close()) land
    WHILE enqueue's slot copy is still running in the executor. The batch
    must be re-routed to a live worker and resolve with results — no
    ValueError, no 500."""
    import time

    cfg = make_cfg(relay_workers=2, relay_epoch_images=8,
                   relay_epoch_ms=150.0)
    model = build(cfg)
    pool = DeferredPool(cfg, "", model)

    orig_write = DeferredPool._write_slot
    slow_from: dict = {"t": None}

    def slow_write(self, w, slot, host_batch):
        # Slow only writes after the first batch has armed the epoch timer,
        # so the retire + results for batch 1 land mid-copy of batch 2.
        if slow_from["t"] is not None:
            time.sleep(0.6)
        return orig_write(self, w, slot, host_batch)

    monkeypatch.setattr(DeferredPool, "_write_slot", slow_write)

    pool.prewarm()
    loop = asyncio.new_event_loop()
    loop.run_until_complete(pool.start())
    try:
        async def go():
            fut1 = await pool.enqueue((4,), batch(4, seed=1))
            slow_from["t"] = time.perf_counter()
            w1 = pool._active
            # copy spans the retire (4 rows: full bucket, matching the slot)
            fut2 = await pool.enqueue((4,), batch(4, seed=2))
            out1, out2 = await asyncio.wait_for(
                asyncio.gather(fut1, fut2), timeout=120)
            assert out1["probs"].shape == (4, 3)
            assert out2["probs"].shape == (4, 3)
            # The interleave actually happened: worker 1 was retired by the
            # deadline while batch 2 was being written.
            assert w1.retired

        loop.run_until_complete(go())
    finally:
        loop.run_until_complete(pool.stop())
        loop.close()


def test_warm_pool_replenishes_in_background():
    """Activation consumes warm workers; the pool must top itself back up in
    the background so later rotations find a prewarmed successor instead of
    paying a synchronous spawn (stats: workers_prespawned moves, and many
    rotations don't mean many dry respawns)."""
    cfg = make_cfg(relay_workers=2, relay_epoch_images=4, relay_epoch_ms=5_000.0)
    model = build(cfg)
    pool = DeferredPool(cfg, "", model)
    pool.prewarm()
    loop = asyncio.new_event_loop()
    loop.run_until_complete(pool.start())
    try:
        async def go():
            futs = []
            # 6 epochs of one full 4-row batch each: the 2 prewarmed workers
            # cover the first two; the rest need replenished spares.
            for i in range(6):
                futs.append(await pool.enqueue((4,), batch(4, seed=i)))
            outs = await asyncio.wait_for(asyncio.gather(*futs), timeout=120)
            assert len(outs) == 6
            # allow the last background spawn to land
            for _ in range(100):
                if pool.stats["workers_prespawned"] >= 2:
                    break
                await asyncio.sleep(0.1)
            assert pool.stats["workers_prespawned"] >= 2, pool.stats

        loop.run_until_complete(go())
    finally:
        loop.run_until_complete(pool.stop())
        loop.close()
