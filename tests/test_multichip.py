"""Multi-chip serving: replica-per-chip and sharded-batch dispatch (ISSUE 7).

Runs on the suite's 8 fake XLA host devices (conftest forces
``--xla_force_host_platform_device_count=8``), so every contract here is
proven without TPU hardware:

- the ``[parallel]`` plan selects devices, overrides per-model modes, and
  sizes the sharded data axis;
- EVERY replica receives batches under sustained load (least-loaded pick +
  least-loaded fallback — the fixed index-order scan starved high-index
  replicas);
- sharded-batch results are bit-identical to replica-mode results;
- publish/rollback under load is version-atomic across replicas: no
  response ever reflects a mix, and no replica lags on the old tree;
- the staged canary proves the candidate on every replica;
- per-chip attribution (replica_batches_total / replica_inflight /
  per_replica occupancy) is live in /stats and /metrics.
"""

import asyncio
import concurrent.futures as cf
import io

import jax
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.batcher import ModelBatcher
from tpuserve.config import ModelConfig, ParallelConfig, ServerConfig
from tpuserve.models import build
from tpuserve.obs import Metrics
from tpuserve.parallel.mesh import select_devices
from tpuserve.runtime import build_runtime
from tpuserve.server import ServerState, make_app

N_DEV = len(jax.devices())


def toy_cfg(**kw) -> ModelConfig:
    base = dict(name="toy", family="toy", batch_buckets=[1, 2],
                deadline_ms=2.0, dtype="float32", num_classes=10,
                parallelism="replica", request_timeout_ms=30_000.0,
                max_queue=4096)
    base.update(kw)
    return ModelConfig(**base)


def npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


# -- [parallel] plan ---------------------------------------------------------

def test_parallel_config_validation():
    assert ParallelConfig().mode == ""
    with pytest.raises(ValueError, match="parallel.mode"):
        ParallelConfig(mode="pipeline")
    with pytest.raises(ValueError, match="parallel.mode"):
        ParallelConfig(mode="bogus")
    with pytest.raises(ValueError, match="n_chips"):
        ParallelConfig(n_chips=-1)


def test_select_devices():
    assert len(select_devices(0)) == N_DEV
    assert len(select_devices(4)) == 4
    # The first n in stable order, so replica indices map to the same
    # physical chips across restarts.
    assert select_devices(4) == jax.devices()[:4]
    with pytest.raises(ValueError, match="n_chips"):
        select_devices(N_DEV + 1)


def test_n_chips_bounds_replica_and_sharded_meshes():
    rt4 = build_runtime(build(toy_cfg(name="toy4", batch_buckets=[1])),
                        parallel=ParallelConfig(n_chips=4))
    assert rt4.n_replicas == 4 and rt4.n_chips == 4

    # `data` alone sizes a sharded mesh to exactly data*tp*sp chips.
    rts = build_runtime(
        build(toy_cfg(name="toys", parallelism="sharded", batch_buckets=[4])),
        parallel=ParallelConfig(data=4))
    assert rts.n_replicas == 1 and rts.n_chips == 4
    assert rts.meshes[0].shape["data"] == 4
    assert rts.parallel_signature == "sharded@d4"


def test_server_parallel_mode_overrides_models():
    cfg = ServerConfig(
        models=[toy_cfg(parallelism="single", batch_buckets=[1])],
        parallel=ParallelConfig(mode="replica"),
        decode_threads=2, startup_canary=False)
    state = ServerState(cfg)
    state.build()
    rt = state.runtimes["toy"]
    assert rt.mode == "replica"
    assert rt.n_replicas == N_DEV
    assert cfg.models[0].parallelism == "replica"  # config-level override


# -- least-loaded replica pick ------------------------------------------------

def test_pick_replica_least_loaded_and_tie_rotation():
    rt = build_runtime(build(toy_cfg(batch_buckets=[1])))
    assert rt.n_replicas == N_DEV
    # Least-loaded wins outright.
    loads = [3] * N_DEV
    loads[5] = 0
    assert rt.pick_replica(loads) == 5
    # Ties rotate via the round-robin cursor: equal loads must not pin to
    # one replica.
    picks = {rt.pick_replica([0] * N_DEV) for _ in range(N_DEV)}
    assert len(picks) > 1
    # No loads = plain round-robin (prewarm/canary path).
    assert 0 <= rt.pick_replica() < N_DEV


class _FakeStagedRuntime:
    """n-replica runtime stub for batcher staging tests: pick_replica is
    pinned so the test controls the first choice."""

    def __init__(self, n: int, first: int) -> None:
        self.n_replicas = n
        self._first = first
        self.h2d_sync = False

    def pick_replica(self, loads=None) -> int:
        return self._first

    def replica_batches(self):
        return [0.0] * self.n_replicas


def test_acquire_staging_falls_back_least_loaded_not_index_order():
    """When the first-choice pool is exhausted, the fallback must take the
    LEAST-LOADED remaining pool — the old fixed (first+k)%n scan handed the
    batch to the next index, starving high-index replicas under bursts."""
    model = build(toy_cfg(batch_buckets=[1]))
    rt = _FakeStagedRuntime(3, first=0)
    pool = cf.ThreadPoolExecutor(max_workers=1)

    async def go():
        b = ModelBatcher(model, rt, Metrics(), pool)
        await b.start()
        try:
            assert len(b._staging) == 3
            # Exhaust pool 0 (the pinned first choice); load pool 1 with
            # one batch; leave pool 2 empty.
            while b._staging[0].try_acquire() is not None:
                pass
            b._staging[1].try_acquire()
            replica, slot = await b._acquire_staging([])
            assert replica == 2, (
                f"fallback took replica {replica}; index-order scan would "
                "take 1, least-loaded must take 2")
            b._release_staging(replica, slot)
        finally:
            await b.stop()

    asyncio.run(go())
    pool.shutdown()


# -- every replica serves under load ------------------------------------------

def test_every_replica_receives_batches_under_sustained_load():
    model = build(toy_cfg(batch_buckets=[1]))
    metrics = Metrics()
    rt = build_runtime(build(toy_cfg(batch_buckets=[1])), metrics=metrics)
    assert rt.n_replicas == N_DEV
    pool = cf.ThreadPoolExecutor(max_workers=2)

    async def go():
        b = ModelBatcher(model, rt, metrics, pool)
        await b.start()
        # Replica-aware admission: depth x replicas + assemble_ahead.
        assert b._admission_cap == b.depth * N_DEV + b.pipeline_cfg.assemble_ahead
        try:
            rng = np.random.default_rng(0)
            items = [rng.integers(0, 255, (8, 8, 3), np.uint8)
                     for _ in range(12 * N_DEV)]
            results = await asyncio.gather(*[b.submit(it) for it in items])
            assert len(results) == 12 * N_DEV
            assert all(r["top_k"] for r in results)
        finally:
            await b.stop()

    asyncio.run(go())
    pool.shutdown()
    batches = rt.replica_batches()
    assert len(batches) == N_DEV
    assert all(v > 0 for v in batches), (
        f"starved replica(s): {batches} — the batcher must keep every "
        "chip's staging slots fed")
    # Occupancy gauges exist per replica and ended drained.
    for i in range(N_DEV):
        assert metrics.gauge(
            f"replica_inflight{{model=toy,replica={i}}}").value == 0


# -- sharded vs replica parity ------------------------------------------------

def test_sharded_batch_results_bit_identical_to_replica_mode():
    bucket = (N_DEV,)
    rng = np.random.default_rng(7)
    items = [rng.integers(0, 255, (8, 8, 3), np.uint8) for _ in range(N_DEV)]

    rt_rep = build_runtime(
        build(toy_cfg(name="t-rep", batch_buckets=[N_DEV])))
    rt_sh = build_runtime(
        build(toy_cfg(name="t-sh", parallelism="sharded",
                      batch_buckets=[N_DEV])))
    assert rt_sh.meshes[0].shape["data"] == N_DEV
    model = build(toy_cfg(batch_buckets=[N_DEV]))
    batch = model.assemble(items, bucket)
    out_sh = rt_sh.fetch(rt_sh.run(bucket, batch))
    for replica in range(rt_rep.n_replicas):
        out_rep = rt_rep.fetch(rt_rep.run(bucket, batch, replica=replica))
        np.testing.assert_array_equal(out_sh["probs"], out_rep["probs"])
        np.testing.assert_array_equal(out_sh["indices"], out_rep["indices"])


def test_variant_key_parallelism_composes_with_quantize():
    """The parallelism dimension of the VariantKey carries the device
    layout (ISSUE 7) and composes with dtype/quantize — and version churn
    across a replica set recompiles NOTHING (the zero-recompile proof
    obligation extends to multi-chip)."""
    metrics = Metrics()
    rt = build_runtime(
        build(toy_cfg(batch_buckets=[1], quantize="int8",
                      quantize_min_size=16)),
        metrics=metrics)
    assert rt.parallel_signature == f"replica@{N_DEV}"
    key = rt.variant_key((1,))
    assert key.parallelism == f"replica@{N_DEV}"
    assert key.label == f"1/float32/int8/replica@{N_DEV}"
    before = rt.compiles_total
    assert before == len(rt.model.buckets()) * N_DEV
    staged = rt.stage_params()
    rt.publish(staged)
    rt.rollback()
    assert rt.ensure_compiled() == 0
    assert rt.compiles_total == before


# -- lifecycle atomicity across replicas --------------------------------------

def _scaled(trees, factor):
    return [jax.tree_util.tree_map(lambda x: x * factor, t) for t in trees]


def test_publish_rollback_under_load_never_serves_torn_versions():
    """Sustained single-item load over all replicas while a publish and a
    rollback land mid-flight: every response must equal EXACTLY the v1 or
    the v2 reference (never a mix, never a third value), and after each
    transition the steady state must be the new version on every replica."""
    model = build(toy_cfg(batch_buckets=[1]))
    rt = build_runtime(build(toy_cfg(batch_buckets=[1])))
    assert rt.n_replicas == N_DEV
    pool = cf.ThreadPoolExecutor(max_workers=2)
    item = np.random.default_rng(3).integers(0, 255, (8, 8, 3), np.uint8)

    def probs(r):
        return np.array([e["prob"] for e in r["top_k"]], np.float64)

    def version_of(r, ref_v1, ref_v2):
        """1 or 2 when the response matches exactly one version reference
        (tight tolerance — replica executables are compiled per device);
        fails the test for a torn/mixed/third answer."""
        m1 = np.allclose(probs(r), probs(ref_v1), rtol=1e-6, atol=1e-9)
        m2 = np.allclose(probs(r), probs(ref_v2), rtol=1e-6, atol=1e-9)
        assert m1 != m2, (
            f"response matches {'both versions' if m1 else 'neither version'}"
            f" — torn or mixed weights served: {r}")
        return 1 if m1 else 2

    async def go():
        b = ModelBatcher(model, rt, Metrics(), pool)
        await b.start()
        try:
            ref_v1 = await b.submit(item.copy())
            staged = _scaled(rt.params_per_mesh, 1.5)

            async def burst(n):
                return await asyncio.gather(
                    *[b.submit(item.copy()) for _ in range(n)])

            # Publish races a burst across every replica.
            burst_task = asyncio.ensure_future(burst(6 * N_DEV))
            await asyncio.sleep(0.01)
            rt.publish(staged)
            mixed = await burst_task
            ref_v2 = await b.submit(item.copy())
            # The two versions are far apart relative to the match
            # tolerance: scaling by 1.5 moves the softmax visibly.
            assert not np.allclose(probs(ref_v1), probs(ref_v2), rtol=1e-3)
            for r in mixed:
                version_of(r, ref_v1, ref_v2)
            # Steady state post-publish: EVERY replica answers v2.
            for _ in range(2 * N_DEV):
                r = await b.submit(item.copy())
                assert version_of(r, ref_v1, ref_v2) == 2
            assert all(v > 0 for v in rt.replica_batches())

            # Rollback races a burst the same way.
            burst_task = asyncio.ensure_future(burst(6 * N_DEV))
            await asyncio.sleep(0.01)
            rt.rollback()
            mixed = await burst_task
            for r in mixed:
                version_of(r, ref_v1, ref_v2)
            for _ in range(2 * N_DEV):
                r = await b.submit(item.copy())
                assert version_of(r, ref_v1, ref_v2) == 1
        finally:
            await b.stop()

    asyncio.run(go())
    pool.shutdown()


def test_staged_canary_proves_every_replica():
    """A candidate copy corrupted on ONE replica must fail the staged
    canary gate — serving an eighth of the traffic from a poisoned tree is
    exactly the torn state the lifecycle exists to prevent."""
    from tpuserve.config import LifecycleConfig
    from tpuserve.lifecycle import ModelLifecycle

    model = build(toy_cfg(batch_buckets=[1]))
    rt = build_runtime(model)
    assert rt.n_replicas == N_DEV
    lc = ModelLifecycle("toy", rt, model, LifecycleConfig(), Metrics())
    poisoned = rt.n_replicas - 1  # high replica: replica-0-only canaries miss it
    staged = _scaled(rt.params_per_mesh, 1.0)
    staged[poisoned] = jax.tree_util.tree_map(
        lambda x: x * np.nan, staged[poisoned])
    with pytest.raises(ValueError, match=f"replica {poisoned}"):
        lc._staged_canary_sync(staged)
    # A clean candidate passes on all replicas.
    lc._staged_canary_sync(_scaled(rt.params_per_mesh, 1.5))


# -- observability over HTTP ---------------------------------------------------

def test_stats_parallel_block_and_per_replica_over_http():
    cfg = ServerConfig(
        models=[toy_cfg(batch_buckets=[1])],
        decode_threads=2, startup_canary=False)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()
    try:
        async def go():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                rng = np.random.default_rng(5)
                for _ in range(4 * N_DEV):
                    r = await client.post(
                        "/v1/models/toy:classify",
                        data=npy_bytes(
                            rng.integers(0, 255, (8, 8, 3), np.uint8)),
                        headers={"Content-Type": "application/x-npy"})
                    assert r.status == 200
                stats = await (await client.get("/stats")).json()
                metrics_text = await (await client.get("/metrics")).text()
                models = await (await client.get("/v1/models")).json()
                return stats, metrics_text, models
            finally:
                await client.close()

        stats, metrics_text, models = loop.run_until_complete(go())
    finally:
        loop.close()

    par = stats["parallel"]["toy"]
    assert par["mode"] == "replica"
    assert par["signature"] == f"replica@{N_DEV}"
    assert par["n_chips"] == N_DEV and par["replicas"] == N_DEV
    assert len(par["replica_batches_total"]) == N_DEV
    assert sum(par["replica_batches_total"]) > 0
    assert par["batches_per_chip"] == pytest.approx(
        sum(par["replica_batches_total"]) / N_DEV)

    per_rep = stats["pipeline"]["models"]["toy"]["per_replica"]
    assert [row["replica"] for row in per_rep] == list(range(N_DEV))
    for row in per_rep:
        assert 0.0 <= row["occupancy"] <= 1.0
        assert row["batches_total"] is not None

    assert 'replica_batches_total{model="toy",replica="0"}' in metrics_text
    assert 'replica_inflight{model="toy",replica="0"}' in metrics_text
    assert models["toy"]["n_chips"] == N_DEV
    assert models["toy"]["parallel"] == f"replica@{N_DEV}"


# -- bench helpers -------------------------------------------------------------

def test_build_roofline_aggregate_chip_ceiling():
    from tpuserve.bench import roofline as rl

    latency = {
        "latency_ms{model=m,phase=compute}": {"n": 10, "p50_ms": 100.0},
    }
    block = rl.build_roofline(
        latency, "m", buckets=[8], raw_ms_by_bucket={8: 10.0},
        link_mbps=10.0, img_bytes=1000, chip_img_s=1000.0,
        value_img_s=4000.0, n_chips=8)
    assert block["chip_ceiling_img_s"] == 1000.0
    assert block["aggregate_chip_ceiling_img_s"] == 8000.0
    assert block["n_chips"] == 8
    # 4000 of 8x1000: half the MESH's ceiling, not 400% of one chip's.
    assert block["pct_of_chip_ceiling"] == pytest.approx(50.0)
    # Single-chip default unchanged (back-compat with every prior BENCH_r).
    single = rl.build_roofline(
        latency, "m", buckets=[8], raw_ms_by_bucket={8: 10.0},
        link_mbps=10.0, img_bytes=1000, chip_img_s=1000.0,
        value_img_s=500.0)
    assert single["pct_of_chip_ceiling"] == pytest.approx(50.0)
    assert single["n_chips"] == 1
