"""Batching engine behavior (C2): flush-on-full, flush-on-deadline, padding,
fault containment, load shedding, cancellation. SURVEY.md §4-2."""

import asyncio
import concurrent.futures as cf

import numpy as np
import pytest

from tpuserve.batcher import ModelBatcher, QueueFull
from tpuserve.config import ModelConfig
from tpuserve.faults import FaultInjected, FaultInjector
from tpuserve.models import build
from tpuserve.obs import Metrics
from tpuserve.runtime import build_runtime


@pytest.fixture(scope="module")
def rt_model():
    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                      deadline_ms=30.0, dtype="float32", num_classes=10,
                      parallelism="single", max_queue=16)
    model = build(cfg)
    rt = build_runtime(model)
    return model, rt


def make_batcher(rt_model, **cfg_over):
    model, rt = rt_model
    for k, v in cfg_over.items():
        setattr(model.cfg, k, v)
    metrics = Metrics()
    pool = cf.ThreadPoolExecutor(max_workers=4)
    return ModelBatcher(model, rt, metrics, pool), metrics


def item():
    return np.random.default_rng(0).integers(0, 255, (8, 8, 3), dtype=np.uint8)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_flush_on_full(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=10_000.0)  # deadline effectively off
        await b.start()
        futs = [b.submit(item()) for _ in range(4)]  # == max bucket
        res = await asyncio.wait_for(asyncio.gather(*futs), timeout=10)
        await b.stop()
        assert len(res) == 4
        assert all("top_k" in r for r in res)
        assert metrics.counter("batches_total{model=toy}").value == 1

    run(go())


def test_flush_on_deadline(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=25.0)
        await b.start()
        fut = b.submit(item())  # single request, batch can't fill
        res = await asyncio.wait_for(fut, timeout=10)
        await b.stop()
        assert "top_k" in res
        # padded to the smallest bucket (1) => fill ratio 1.0
        assert metrics.gauge("batch_fill_ratio{model=toy}").value == 1.0

    run(go())


def test_partial_batch_padding(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=25.0)
        await b.start()
        futs = [b.submit(item()) for _ in range(3)]  # pads to bucket 4
        res = await asyncio.wait_for(asyncio.gather(*futs), timeout=10)
        await b.stop()
        assert len(res) == 3
        assert metrics.gauge("batch_fill_ratio{model=toy}").value == 0.75

    run(go())


def test_fault_containment(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=20.0)
        await b.start()
        b.injector = FaultInjector.single("batch_error", metrics=metrics)
        fut = b.submit(item())
        with pytest.raises(FaultInjected, match="injected fault"):
            await asyncio.wait_for(fut, timeout=10)
        assert metrics.counter("batch_errors_total{model=toy}").value == 1
        # server keeps serving after the failed batch
        b.injector = None
        res = await asyncio.wait_for(b.submit(item()), timeout=10)
        assert "top_k" in res
        await b.stop()

    run(go())


def test_transient_fault_retried_transparently(rt_model):
    """batch_retry: a fault that fires once is absorbed by the one-shot
    retry — the client sees a normal result, not a 500."""
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=20.0, batch_retry=True)
        await b.start()
        b.injector = FaultInjector.single("batch_error", count=1,
                                          metrics=metrics)
        res = await asyncio.wait_for(b.submit(item()), timeout=10)
        assert "top_k" in res
        assert metrics.counter("batch_errors_total{model=toy}").value == 1
        assert metrics.counter("batch_retries_total{model=toy}").value == 1
        assert metrics.counter("batch_retry_failures_total{model=toy}").value == 0
        await b.stop()

    run(go())


class _PoisonModel:
    """Delegating wrapper whose assemble raises when a poison item (all-255
    image) is in the batch — the whole-batch failure mode a single bad
    request induces."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def assemble(self, items, bucket):
        if any(int(np.min(it)) == 255 for it in items):
            raise RuntimeError("poison item in batch")
        return self._inner.assemble(items, bucket)


def test_poison_item_isolated_by_split_retry(rt_model):
    """Split retry: one poison item in a full batch fails ONLY its own
    future; every other lane succeeds after the bisection."""
    async def go():
        model, rt = rt_model
        for k, v in dict(deadline_ms=10_000.0, max_queue=16, batch_retry=True,
                         retry_split=True).items():
            setattr(model.cfg, k, v)
        metrics = Metrics()
        pool = cf.ThreadPoolExecutor(max_workers=4)
        b = ModelBatcher(_PoisonModel(model), rt, metrics, pool)
        await b.start()
        good = [b.submit(item()) for _ in range(3)]
        poison = b.submit(np.full((8, 8, 3), 255, dtype=np.uint8))
        results = await asyncio.wait_for(
            asyncio.gather(*good, poison, return_exceptions=True), timeout=30)
        await b.stop()
        assert all("top_k" in r for r in results[:3])
        assert isinstance(results[3], RuntimeError)
        assert "poison" in str(results[3])
        assert metrics.counter("poison_items_total{model=toy}").value == 1
        assert metrics.counter("batch_retries_total{model=toy}").value == 1

    run(go())


def test_load_shedding(rt_model):
    """Real shedding behavior: with the deadline far out and the bucket not
    full, pending requests pile up and the (max_queue+1)th submit 429s."""
    async def go():
        b, metrics = make_batcher(rt_model, max_queue=2, deadline_ms=10_000.0)
        await b.start()
        f1 = b.submit(item())
        f2 = b.submit(item())
        await asyncio.sleep(0.05)  # group loop runs; batch (max 4) not full
        with pytest.raises(QueueFull):
            b.submit(item())
        assert metrics.counter("shed_total{model=toy}").value == 1
        f1.cancel(), f2.cancel()
        await b.stop()

    run(go())


def test_submit_before_start_raises(rt_model):
    b, _ = make_batcher(rt_model)
    with pytest.raises(RuntimeError, match="not started"):
        b.submit(item())


def test_stop_fails_queued_futures(rt_model):
    """Requests still queued at stop() resolve with an error, never hang
    (ADVICE r1: stop() cleared queues without failing futures)."""
    async def go():
        b, _ = make_batcher(rt_model, max_queue=16, deadline_ms=10_000.0)
        await b.start()
        futs = [b.submit(item()) for _ in range(2)]
        await b.stop()
        for f in futs:
            assert f.done()
            assert isinstance(f.exception(), RuntimeError) or f.cancelled()

    run(go())


def test_cancelled_requests_skipped(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=40.0, max_queue=16)
        await b.start()
        f1 = b.submit(item())
        f2 = b.submit(item())
        f1.cancel()
        res = await asyncio.wait_for(f2, timeout=10)
        assert "top_k" in res
        await b.stop()

    run(go())


def test_deadline_expired_in_queue_fails_fast(rt_model):
    """P3 discipline: a request whose per-request deadline passes while it
    waits behind slow in-flight work fails AT its deadline with
    DeadlineExceeded — never dispatched — while undeadlined work survives."""
    import time

    from tpuserve.batcher import DeadlineExceeded

    async def go():
        model, _ = rt_model
        b, metrics = make_batcher(rt_model, deadline_ms=20.0, max_inflight=1)
        await b.start()
        try:
            # One-shot 400 ms dispatch stall occupies the single slot.
            b.injector = FaultInjector.single("slow_dispatch",
                                              delay_ms=400.0, count=1)
            slow = b.submit(item())
            await asyncio.sleep(0.05)  # dispatched, slot held
            t0 = time.perf_counter()
            doomed = b.submit(item(), deadline_at=t0 + 0.05)
            with pytest.raises(DeadlineExceeded, match="deadline expired"):
                await asyncio.wait_for(doomed, timeout=10)
            waited = time.perf_counter() - t0
            assert waited < 0.3, waited  # failed AT the deadline, not at slot free
            assert metrics.counter(
                "deadline_exceeded_total{model=toy}").value == 1
            assert "top_k" in await asyncio.wait_for(slow, timeout=10)
            # Queue drained cleanly: later requests still serve.
            res = await asyncio.wait_for(b.submit(item()), timeout=10)
            assert "top_k" in res
            assert b._pending == 0
        finally:
            await b.stop()
            model.cfg.max_inflight = 2  # module-scoped cfg: restore default

    run(go())


def test_generous_deadline_dispatches_normally(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=20.0)
        await b.start()
        import time

        fut = b.submit(item(), deadline_at=time.perf_counter() + 30.0)
        res = await asyncio.wait_for(fut, timeout=10)
        assert "top_k" in res
        assert metrics.counter(
            "deadline_exceeded_total{model=toy}").value == 0
        await b.stop()

    run(go())


# ---------------------------------------------------------------------------
# SLO-aware adaptive batching (ISSUE 5): AIMD target + EWMA-bounded flush
# ---------------------------------------------------------------------------

def make_adaptive_batcher(rt_model, adaptive, **cfg_over):
    from tpuserve.config import AdaptiveConfig

    model, rt = rt_model
    cfg_over.setdefault("max_inflight", 2)
    for k, v in cfg_over.items():
        setattr(model.cfg, k, v)
    metrics = Metrics()
    pool = cf.ThreadPoolExecutor(max_workers=4)
    acfg = adaptive if isinstance(adaptive, AdaptiveConfig) else AdaptiveConfig(**adaptive)
    return ModelBatcher(model, rt, metrics, pool, adaptive_cfg=acfg), metrics


def test_aimd_grows_on_pressure_shrinks_on_timer():
    """Unit dynamics: a batch filled to target with work still queued grows
    the target additively toward the largest bucket; a timer-driven partial
    flush shrinks it multiplicatively toward min_target — the AIMD sawtooth
    that makes the scheduler bimodal. A fill with an EMPTY queue is
    equilibrium: no growth (lone sequential requests at target 1 must not
    flap between immediate and full-timer flushes)."""
    from tpuserve.config import AdaptiveConfig, ModelConfig
    from tpuserve.models import build as build_model
    from tpuserve.runtime import build_runtime as _brt

    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                      deadline_ms=30.0, dtype="float32", num_classes=10,
                      parallelism="single")
    model = build_model(cfg)
    b = ModelBatcher(model, _brt(model), Metrics(),
                     cf.ThreadPoolExecutor(max_workers=2),
                     adaptive_cfg=AdaptiveConfig(increase=1.0, decrease=0.5))
    g = None
    b._aimd_update(g, 2.0, n=2, target_n=2, timer_flush=False, pressure=True)
    assert b._targets[g] == 3.0
    b._aimd_update(g, 4.0, n=4, target_n=4, timer_flush=False, pressure=True)
    assert b._targets[g] == 4.0  # clamped to the largest bucket
    b._aimd_update(g, 1.0, n=1, target_n=1, timer_flush=False, pressure=False)
    assert b._targets[g] == 1.0  # equilibrium fill: steady, no flap
    b._aimd_update(g, 4.0, n=1, target_n=4, timer_flush=True, pressure=False)
    assert b._targets[g] == 2.0  # starved: multiplicative shrink
    b._aimd_update(g, 1.2, n=1, target_n=2, timer_flush=True, pressure=False)
    assert b._targets[g] == 1.0  # floored at min_target
    # A partial flush NOT driven by the timer (e.g. drain) leaves it alone.
    b._aimd_update(g, 2.0, n=1, target_n=2, timer_flush=False, pressure=False)
    assert b._targets[g] == 2.0


def test_batch_duration_ewma_tracks_observations():
    """First observation seeds the EWMA; later ones blend by alpha. The
    gauge mirrors it so dashboards see the scheduler's duration model."""
    from tpuserve.config import AdaptiveConfig, ModelConfig
    from tpuserve.models import build as build_model
    from tpuserve.runtime import build_runtime as _brt

    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                      deadline_ms=30.0, dtype="float32", num_classes=10,
                      parallelism="single")
    model = build_model(cfg)
    metrics = Metrics()
    b = ModelBatcher(model, _brt(model), metrics,
                     cf.ThreadPoolExecutor(max_workers=2),
                     adaptive_cfg=AdaptiveConfig(ewma_alpha=0.5))
    b._observe_batch_duration((4,), 10.0)
    assert b._ewma_ms[(4,)] == 10.0
    b._observe_batch_duration((4,), 20.0)
    assert b._ewma_ms[(4,)] == 15.0  # 10 + 0.5 * (20 - 10)
    assert metrics.gauge("batch_duration_ewma_ms{model=toy}").value == 15.0
    # Buckets keep independent duration models.
    b._observe_batch_duration((1,), 2.0)
    assert b._ewma_ms[(4,)] == 15.0 and b._ewma_ms[(1,)] == 2.0


def test_flush_headroom_from_earliest_deadline():
    """Clockwork-style bound: the batch must dispatch while ~EWMA + slack
    still fits before the earliest member deadline; no deadlines => +inf."""
    import time as _time

    from tpuserve.batcher import _Request
    from tpuserve.config import AdaptiveConfig, ModelConfig
    from tpuserve.models import build as build_model
    from tpuserve.runtime import build_runtime as _brt

    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                      deadline_ms=30.0, dtype="float32", num_classes=10,
                      parallelism="single")
    model = build_model(cfg)
    b = ModelBatcher(model, _brt(model), Metrics(),
                     cf.ThreadPoolExecutor(max_workers=2),
                     adaptive_cfg=AdaptiveConfig(slack_ms=2.0))

    async def go():
        loop = asyncio.get_running_loop()

        def req(deadline_at):
            return _Request(item=item(), future=loop.create_future(),
                            group=None, enqueued_at=_time.perf_counter(),
                            deadline_at=deadline_at)

        assert b._flush_headroom([req(None)]) == float("inf")
        now = _time.perf_counter()
        b._ewma_ms[(2,)] = 8.0  # the 2-item batch rounds up to bucket (2,)
        got = b._flush_headroom([req(now + 0.100), req(None)])
        # deadline - (8 ms EWMA + 2 ms slack) = 90 ms from "now".
        assert got == pytest.approx(now + 0.090, abs=5e-4)

    run(go())


def test_adaptive_light_load_flushes_before_max_wait(rt_model):
    """Bimodal, light side: after timer flushes shrink the target to 1,
    lone requests flush immediately instead of waiting out deadline_ms —
    p50 well under the fixed-timer baseline measured in the same test."""
    import time as _time

    from tpuserve.config import AdaptiveConfig

    async def sequential_p50(b) -> float:
        lats = []
        for _ in range(5):
            t0 = _time.perf_counter()
            await asyncio.wait_for(b.submit(item()), timeout=10)
            lats.append(_time.perf_counter() - t0)
        return sorted(lats)[len(lats) // 2]

    async def go():
        # Fixed-timer baseline: every lone request waits out deadline_ms.
        b, _ = make_adaptive_batcher(rt_model, AdaptiveConfig(enabled=False),
                                     deadline_ms=120.0)
        await b.start()
        fixed_p50 = await sequential_p50(b)
        await b.stop()
        assert fixed_p50 >= 0.110, fixed_p50  # sanity: timer really waited

        b, metrics = make_adaptive_batcher(
            rt_model, AdaptiveConfig(enabled=True, decrease=0.25),
            deadline_ms=120.0)
        await b.start()
        # Warm-down: the first lone flushes are timer-driven and shrink the
        # target 4 -> 1; discard them like a bench warmup.
        await sequential_p50(b)
        assert b._targets[None] == 1.0
        adaptive_p50 = await sequential_p50(b)
        await b.stop()
        assert adaptive_p50 < fixed_p50 / 2, (adaptive_p50, fixed_p50)
        assert metrics.gauge("adaptive_target_batch{model=toy}").value == 1.0

    run(go())


def test_adaptive_saturated_load_fills_buckets(rt_model):
    """Bimodal, heavy side: with the queue never empty the AIMD target sits
    at the largest bucket and batches fill — mean batch size >= 0.9x."""
    from tpuserve.config import AdaptiveConfig

    async def go():
        b, metrics = make_adaptive_batcher(
            rt_model, AdaptiveConfig(enabled=True), deadline_ms=50.0,
            max_queue=64)
        await b.start()
        futs = [b.submit(item()) for _ in range(32)]
        await asyncio.wait_for(asyncio.gather(*futs), timeout=30)
        await b.stop()
        batches = metrics.counter("batches_total{model=toy}").value
        items = metrics.counter("items_total{model=toy}").value
        assert items == 32
        mean = items / batches
        assert mean >= 0.9 * 4, f"saturated mean batch {mean} (in {batches})"
        # Saturation kept (or grew) the target at the bucket ceiling.
        assert b._targets[None] == 4.0

    run(go())


def test_adaptive_deadline_headroom_preempts_accumulation(rt_model):
    """A lone request whose deadline leaves less headroom than the observed
    batch duration + slack flushes NOW, not at the max-wait timer — and
    beats its deadline instead of discovering it at dispatch."""
    import time as _time

    from tpuserve.config import AdaptiveConfig

    async def go():
        b, metrics = make_adaptive_batcher(
            rt_model,
            AdaptiveConfig(enabled=True, initial_target=4, slack_ms=2.0),
            deadline_ms=5_000.0)  # max-wait timer effectively out of play
        await b.start()
        # Seed the duration model so headroom math has a real estimate.
        await asyncio.wait_for(b.submit(item()), timeout=10)
        b._targets[None] = 4.0  # force re-accumulation despite the flush
        t0 = _time.perf_counter()
        fut = b.submit(item(), deadline_at=t0 + 0.150)
        res = await asyncio.wait_for(fut, timeout=10)
        took = _time.perf_counter() - t0
        await b.stop()
        assert "top_k" in res
        # Flushed by the headroom bound (~150 ms - EWMA - slack), far below
        # the 5 s max-wait; generous margin for CI jitter.
        assert took < 1.0, took
        assert metrics.counter(
            "deadline_exceeded_total{model=toy}").value == 0

    run(go())
