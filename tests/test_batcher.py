"""Batching engine behavior (C2): flush-on-full, flush-on-deadline, padding,
fault containment, load shedding, cancellation. SURVEY.md §4-2."""

import asyncio
import concurrent.futures as cf

import numpy as np
import pytest

from tpuserve.batcher import ModelBatcher, QueueFull
from tpuserve.config import ModelConfig
from tpuserve.faults import FaultInjected, FaultInjector
from tpuserve.models import build
from tpuserve.obs import Metrics
from tpuserve.runtime import build_runtime


@pytest.fixture(scope="module")
def rt_model():
    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                      deadline_ms=30.0, dtype="float32", num_classes=10,
                      parallelism="single", max_queue=16)
    model = build(cfg)
    rt = build_runtime(model)
    return model, rt


def make_batcher(rt_model, **cfg_over):
    model, rt = rt_model
    for k, v in cfg_over.items():
        setattr(model.cfg, k, v)
    metrics = Metrics()
    pool = cf.ThreadPoolExecutor(max_workers=4)
    return ModelBatcher(model, rt, metrics, pool), metrics


def item():
    return np.random.default_rng(0).integers(0, 255, (8, 8, 3), dtype=np.uint8)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_flush_on_full(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=10_000.0)  # deadline effectively off
        await b.start()
        futs = [b.submit(item()) for _ in range(4)]  # == max bucket
        res = await asyncio.wait_for(asyncio.gather(*futs), timeout=10)
        await b.stop()
        assert len(res) == 4
        assert all("top_k" in r for r in res)
        assert metrics.counter("batches_total{model=toy}").value == 1

    run(go())


def test_flush_on_deadline(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=25.0)
        await b.start()
        fut = b.submit(item())  # single request, batch can't fill
        res = await asyncio.wait_for(fut, timeout=10)
        await b.stop()
        assert "top_k" in res
        # padded to the smallest bucket (1) => fill ratio 1.0
        assert metrics.gauge("batch_fill_ratio{model=toy}").value == 1.0

    run(go())


def test_partial_batch_padding(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=25.0)
        await b.start()
        futs = [b.submit(item()) for _ in range(3)]  # pads to bucket 4
        res = await asyncio.wait_for(asyncio.gather(*futs), timeout=10)
        await b.stop()
        assert len(res) == 3
        assert metrics.gauge("batch_fill_ratio{model=toy}").value == 0.75

    run(go())


def test_fault_containment(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=20.0)
        await b.start()
        b.injector = FaultInjector.single("batch_error", metrics=metrics)
        fut = b.submit(item())
        with pytest.raises(FaultInjected, match="injected fault"):
            await asyncio.wait_for(fut, timeout=10)
        assert metrics.counter("batch_errors_total{model=toy}").value == 1
        # server keeps serving after the failed batch
        b.injector = None
        res = await asyncio.wait_for(b.submit(item()), timeout=10)
        assert "top_k" in res
        await b.stop()

    run(go())


def test_transient_fault_retried_transparently(rt_model):
    """batch_retry: a fault that fires once is absorbed by the one-shot
    retry — the client sees a normal result, not a 500."""
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=20.0, batch_retry=True)
        await b.start()
        b.injector = FaultInjector.single("batch_error", count=1,
                                          metrics=metrics)
        res = await asyncio.wait_for(b.submit(item()), timeout=10)
        assert "top_k" in res
        assert metrics.counter("batch_errors_total{model=toy}").value == 1
        assert metrics.counter("batch_retries_total{model=toy}").value == 1
        assert metrics.counter("batch_retry_failures_total{model=toy}").value == 0
        await b.stop()

    run(go())


class _PoisonModel:
    """Delegating wrapper whose assemble raises when a poison item (all-255
    image) is in the batch — the whole-batch failure mode a single bad
    request induces."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def assemble(self, items, bucket):
        if any(int(np.min(it)) == 255 for it in items):
            raise RuntimeError("poison item in batch")
        return self._inner.assemble(items, bucket)


def test_poison_item_isolated_by_split_retry(rt_model):
    """Split retry: one poison item in a full batch fails ONLY its own
    future; every other lane succeeds after the bisection."""
    async def go():
        model, rt = rt_model
        for k, v in dict(deadline_ms=10_000.0, max_queue=16, batch_retry=True,
                         retry_split=True).items():
            setattr(model.cfg, k, v)
        metrics = Metrics()
        pool = cf.ThreadPoolExecutor(max_workers=4)
        b = ModelBatcher(_PoisonModel(model), rt, metrics, pool)
        await b.start()
        good = [b.submit(item()) for _ in range(3)]
        poison = b.submit(np.full((8, 8, 3), 255, dtype=np.uint8))
        results = await asyncio.wait_for(
            asyncio.gather(*good, poison, return_exceptions=True), timeout=30)
        await b.stop()
        assert all("top_k" in r for r in results[:3])
        assert isinstance(results[3], RuntimeError)
        assert "poison" in str(results[3])
        assert metrics.counter("poison_items_total{model=toy}").value == 1
        assert metrics.counter("batch_retries_total{model=toy}").value == 1

    run(go())


def test_load_shedding(rt_model):
    """Real shedding behavior: with the deadline far out and the bucket not
    full, pending requests pile up and the (max_queue+1)th submit 429s."""
    async def go():
        b, metrics = make_batcher(rt_model, max_queue=2, deadline_ms=10_000.0)
        await b.start()
        f1 = b.submit(item())
        f2 = b.submit(item())
        await asyncio.sleep(0.05)  # group loop runs; batch (max 4) not full
        with pytest.raises(QueueFull):
            b.submit(item())
        assert metrics.counter("shed_total{model=toy}").value == 1
        f1.cancel(), f2.cancel()
        await b.stop()

    run(go())


def test_submit_before_start_raises(rt_model):
    b, _ = make_batcher(rt_model)
    with pytest.raises(RuntimeError, match="not started"):
        b.submit(item())


def test_stop_fails_queued_futures(rt_model):
    """Requests still queued at stop() resolve with an error, never hang
    (ADVICE r1: stop() cleared queues without failing futures)."""
    async def go():
        b, _ = make_batcher(rt_model, max_queue=16, deadline_ms=10_000.0)
        await b.start()
        futs = [b.submit(item()) for _ in range(2)]
        await b.stop()
        for f in futs:
            assert f.done()
            assert isinstance(f.exception(), RuntimeError) or f.cancelled()

    run(go())


def test_cancelled_requests_skipped(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=40.0, max_queue=16)
        await b.start()
        f1 = b.submit(item())
        f2 = b.submit(item())
        f1.cancel()
        res = await asyncio.wait_for(f2, timeout=10)
        assert "top_k" in res
        await b.stop()

    run(go())


def test_deadline_expired_in_queue_fails_fast(rt_model):
    """P3 discipline: a request whose per-request deadline passes while it
    waits behind slow in-flight work fails AT its deadline with
    DeadlineExceeded — never dispatched — while undeadlined work survives."""
    import time

    from tpuserve.batcher import DeadlineExceeded

    async def go():
        model, _ = rt_model
        b, metrics = make_batcher(rt_model, deadline_ms=20.0, max_inflight=1)
        await b.start()
        try:
            # One-shot 400 ms dispatch stall occupies the single slot.
            b.injector = FaultInjector.single("slow_dispatch",
                                              delay_ms=400.0, count=1)
            slow = b.submit(item())
            await asyncio.sleep(0.05)  # dispatched, slot held
            t0 = time.perf_counter()
            doomed = b.submit(item(), deadline_at=t0 + 0.05)
            with pytest.raises(DeadlineExceeded, match="deadline expired"):
                await asyncio.wait_for(doomed, timeout=10)
            waited = time.perf_counter() - t0
            assert waited < 0.3, waited  # failed AT the deadline, not at slot free
            assert metrics.counter(
                "deadline_exceeded_total{model=toy}").value == 1
            assert "top_k" in await asyncio.wait_for(slow, timeout=10)
            # Queue drained cleanly: later requests still serve.
            res = await asyncio.wait_for(b.submit(item()), timeout=10)
            assert "top_k" in res
            assert b._pending == 0
        finally:
            await b.stop()
            model.cfg.max_inflight = 2  # module-scoped cfg: restore default

    run(go())


def test_generous_deadline_dispatches_normally(rt_model):
    async def go():
        b, metrics = make_batcher(rt_model, deadline_ms=20.0)
        await b.start()
        import time

        fut = b.submit(item(), deadline_at=time.perf_counter() + 30.0)
        res = await asyncio.wait_for(fut, timeout=10)
        assert "top_k" in res
        assert metrics.counter(
            "deadline_exceeded_total{model=toy}").value == 0
        await b.stop()

    run(go())
