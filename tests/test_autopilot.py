"""Pure-policy tests for the self-healing fleet controller (ISSUE 16).

AutopilotPolicy is a pure function of (Signals, its own bounded memory):
all time comes from ``Signals.now``, so every damping behavior —
hysteresis, per-knob cooldowns, the windowed action budget, and
rollback-on-worse — is table-driven here by constructing signal
sequences. No live server, no sleeps, no clocks.
"""

import asyncio

import pytest

from tpuserve.config import AutopilotConfig
from tpuserve.scheduler.autopilot import (INVERSE, Action, AutopilotLoop,
                                          AutopilotPolicy, DomainSignal,
                                          ModelSignal, Signals, objective)


def ap_cfg(**over) -> AutopilotConfig:
    base = dict(enabled=True, interval_s=0.25, hysteresis_ticks=2,
                cooldown_s=5.0, max_actions_per_window=8, window_s=60.0,
                follow_up_s=10.0, rollback_tolerance=0.5,
                pressure_high=2.0, pressure_low=0.25, min_slots=1)
    base.update(over)
    return AutopilotConfig(**base)


def dom(hid=0, pressure=0.0, active=1, max_slots=2, healthy=1, up=True):
    return DomainSignal(hid=hid, up=up, active=active, max_slots=max_slots,
                        healthy=healthy, pressure=pressure)


def mod(name="m", burn_state="ok", shed_engaged=False, warm=True,
        wants_warm=False, idle=False):
    return ModelSignal(name=name, burn_state=burn_state,
                       shed_engaged=shed_engaged, warm=warm,
                       wants_warm=wants_warm, idle=idle)


def sig(now, domains=(), models=(), clear=0.0):
    return Signals(now=now, domains=list(domains), models=list(models),
                   predicted_clear_s=clear)


def kinds(actions: list[Action]) -> list[str]:
    return [a.kind for a in actions]


# -- objective ----------------------------------------------------------------

@pytest.mark.parametrize("models,domains,expect", [
    ([], [], 0.0),
    ([mod(burn_state="ok")], [dom(pressure=0.5)], 0.5),
    ([mod(burn_state="pending")], [dom(pressure=0.0)], 10.0),
    ([mod(burn_state="firing")], [dom(pressure=1.0)], 21.0),
    # Down domains are excluded from the pressure mean.
    ([], [dom(hid=0, pressure=2.0), dom(hid=1, pressure=0.0, up=False)], 2.0),
    # Worst model dominates; mean over live domains breaks ties.
    ([mod("a", "ok"), mod("b", "firing")],
     [dom(hid=0, pressure=1.0), dom(hid=1, pressure=3.0)], 22.0),
])
def test_objective_scalar(models, domains, expect):
    assert objective(sig(0.0, domains, models)) == pytest.approx(expect)


# -- hysteresis ---------------------------------------------------------------

@pytest.mark.parametrize("pressures,expect_tick", [
    # hysteresis_ticks=3: the third consecutive hot tick acts.
    ([5.0, 5.0, 5.0], 2),
    # One cool sample resets the streak — acts 3 ticks after the gap.
    ([5.0, 5.0, 0.5, 5.0, 5.0, 5.0], 5),
    ([5.0, 0.5, 5.0, 0.5, 5.0, 0.5], None),  # never 3 in a row
])
def test_hysteresis_consecutive_ticks(pressures, expect_tick):
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=3))
    fired_at = None
    for i, pr in enumerate(pressures):
        acts = p.decide(sig(float(i), [dom(pressure=pr)]))
        if acts and fired_at is None:
            fired_at = i
            assert kinds(acts) == ["scale_up"]
    assert fired_at == expect_tick


def test_acting_consumes_the_streak():
    # After an action the SAME trigger must re-accumulate a full streak
    # (cooldown=0 isolates the streak behavior).
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=2, cooldown_s=0.0,
                               follow_up_s=0.0))
    hot = [dom(pressure=5.0, active=1, max_slots=4)]
    assert p.decide(sig(0.0, hot)) == []
    assert kinds(p.decide(sig(1.0, hot))) == ["scale_up"]
    assert p.decide(sig(2.0, hot)) == []  # streak consumed, re-arming
    assert kinds(p.decide(sig(3.0, hot))) == ["scale_up"]


# -- cooldown -----------------------------------------------------------------

def test_cooldown_locks_the_knob_then_releases():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=10.0,
                               follow_up_s=0.0))
    hot = [dom(pressure=5.0, active=1, max_slots=4)]
    assert kinds(p.decide(sig(0.0, hot))) == ["scale_up"]
    # Trigger still held: inside cooldown nothing moves.
    for t in (1.0, 5.0, 9.9):
        assert p.decide(sig(t, hot)) == []
    assert kinds(p.decide(sig(10.0, hot))) == ["scale_up"]


def test_cooldown_is_per_target():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=10.0,
                               follow_up_s=0.0))
    assert kinds(p.decide(sig(0.0, [dom(hid=0, pressure=5.0)]))) \
        == ["scale_up"]
    # A different host's knob is untouched by host 0's cooldown.
    acts = p.decide(sig(1.0, [dom(hid=0, pressure=5.0),
                              dom(hid=1, pressure=5.0)]))
    assert [(a.kind, a.target) for a in acts] == [("scale_up", "host:1")]


# -- action budget ------------------------------------------------------------

def test_budget_caps_actions_per_window_and_reopens():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=0.0,
                               follow_up_s=0.0, max_actions_per_window=2,
                               window_s=60.0))
    hosts = [dom(hid=h, pressure=5.0) for h in range(4)]
    acts = p.decide(sig(0.0, hosts))
    assert len(acts) == 2  # 4 triggers held, budget admits 2
    assert p.budget_deferrals_total == 2
    assert p.decide(sig(1.0, hosts)) == []  # window still full
    # The window slides: 61s later the budget is open again.
    assert len(p.decide(sig(61.0, hosts))) == 2


# -- rollback -----------------------------------------------------------------

def test_rollback_on_worse_objective():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=0.0,
                               follow_up_s=10.0, rollback_tolerance=0.5))
    assert kinds(p.decide(sig(0.0, [dom(pressure=5.0)]))) == ["scale_up"]
    # Follow-up due at t=10; the objective got WORSE (pressure 5 -> 9).
    acts = p.decide(sig(10.0, [dom(pressure=9.0, active=2)]))
    rb = [a for a in acts if a.rollback_of]
    assert len(rb) == 1
    assert rb[0].kind == "scale_down" and rb[0].rollback_of == "scale_up"
    assert rb[0].reason == "rollback"
    assert rb[0].signals["objective_before"] == pytest.approx(5.0)
    assert rb[0].signals["objective_now"] == pytest.approx(9.0)
    assert p.rollbacks_total == 1


@pytest.mark.parametrize("pressure_later", [5.0, 4.0, 5.4])
def test_no_rollback_when_objective_held_or_improved(pressure_later):
    # Within tolerance (0.5) or improved: the action stands.
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=0.0,
                               follow_up_s=10.0, rollback_tolerance=0.5))
    p.decide(sig(0.0, [dom(pressure=5.0)]))
    acts = p.decide(sig(10.0, [dom(pressure=pressure_later, active=2)]))
    assert not [a for a in acts if a.rollback_of]
    assert p.rollbacks_total == 0


def test_rollback_bypasses_budget():
    # Budget exhausted by the original action; the undo must not queue.
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=0.0,
                               follow_up_s=10.0, max_actions_per_window=1,
                               window_s=60.0))
    assert kinds(p.decide(sig(0.0, [dom(pressure=5.0)]))) == ["scale_up"]
    acts = p.decide(sig(10.0, [dom(pressure=9.0, active=2)]))
    assert "scale_down" in kinds(acts)


def test_rollback_cools_both_kinds_no_flap():
    # After an undo, the original trigger (still held) must NOT re-fire
    # the same pair next tick: both knobs of the pair cool.
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=30.0,
                               follow_up_s=10.0))
    p.decide(sig(0.0, [dom(pressure=5.0, active=1, max_slots=4)]))
    # t=30: scale_up's own cooldown has lapsed, so only the rollback's
    # freshly-set cooldown holds the pair down afterwards (the domain
    # keeps headroom, so cooldown is the only thing stopping a re-fire).
    hot = [dom(pressure=9.0, active=2, max_slots=4)]
    acts = p.decide(sig(30.0, hot))
    assert kinds(acts) == ["scale_down"]
    for t in (31.0, 40.0, 59.9):
        assert p.decide(sig(t, hot)) == [], f"flap at t={t}"
    assert kinds(p.decide(sig(60.0, hot))) == ["scale_up"]


# -- shed-on-burn -------------------------------------------------------------

@pytest.mark.parametrize("burn,engaged,expect", [
    ("firing", False, ["shed_on"]),
    ("firing", True, []),   # already engaged
    ("pending", False, []),  # pending never sheds
    ("ok", True, ["shed_off"]),
    ("ok", False, []),
    ("pending", True, []),   # not ok yet: shed stays on
])
def test_shed_decision_table(burn, engaged, expect):
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, follow_up_s=0.0))
    acts = p.decide(sig(0.0, models=[mod("m", burn, engaged)]))
    assert kinds(acts) == expect
    if expect:
        assert acts[0].target == "m"
        assert acts[0].signals["burn_state"] == burn


def test_burn_shed_disabled_by_config():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, burn_shed=False))
    assert p.decide(sig(0.0, models=[mod("m", "firing")])) == []


# -- scale --------------------------------------------------------------------

@pytest.mark.parametrize("d,models,clear,expect", [
    # Hot with headroom -> up; hot at ceiling -> nothing.
    (dom(pressure=5.0, active=1, max_slots=2), [], 0.0, ["scale_up"]),
    (dom(pressure=5.0, active=2, max_slots=2), [], 0.0, []),
    # Cold above the floor -> down; cold at the floor -> nothing.
    (dom(pressure=0.0, active=2, max_slots=2), [], 0.0, ["scale_down"]),
    (dom(pressure=0.0, active=1, max_slots=2), [], 0.0, []),
    # Cold but a model is burning: never scale down into a burn.
    (dom(pressure=0.0, active=2, max_slots=2),
     [mod("m", "pending")], 0.0, []),
    # In the hysteresis band between low and high: hold.
    (dom(pressure=1.0, active=1, max_slots=2), [], 0.0, []),
    # Down domains are never scaled.
    (dom(pressure=5.0, active=1, max_slots=2, up=False), [], 0.0, []),
])
def test_scale_decision_table(d, models, clear, expect):
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, follow_up_s=0.0))
    acts = p.decide(sig(0.0, [d], models, clear=clear))
    assert kinds(acts) == expect
    if expect:
        assert acts[0].target == f"host:{d.hid}"


def test_clear_time_trigger():
    cfg = ap_cfg(hysteresis_ticks=1, follow_up_s=0.0, clear_high_s=5.0)
    p = AutopilotPolicy(cfg)
    # Pressure is calm but the predicted clear time is hot: scale up, and
    # the same signal vetoes any scale-down.
    acts = p.decide(sig(0.0, [dom(pressure=0.0, active=1, max_slots=2)],
                        clear=9.0))
    assert kinds(acts) == ["scale_up"]
    assert acts[0].signals["predicted_clear_s"] == pytest.approx(9.0)
    p2 = AutopilotPolicy(cfg)
    assert p2.decide(sig(0.0, [dom(pressure=0.0, active=2, max_slots=2)],
                         clear=9.0)) == []


def test_scale_disabled_by_config():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, scale=False))
    assert p.decide(sig(0.0, [dom(pressure=9.0)])) == []


# -- paging -------------------------------------------------------------------

@pytest.mark.parametrize("m,max_warm,expect", [
    (mod("m", warm=False, wants_warm=True), 0, ["warm"]),
    (mod("m", warm=True, wants_warm=True), 0, []),      # already warm
    (mod("m", warm=True, idle=True), 0, ["demote"]),
    (mod("m", warm=True, idle=True, wants_warm=True), 0, []),  # demand wins
    (mod("m", warm=False, wants_warm=False), 0, []),
])
def test_paging_decision_table(m, max_warm, expect):
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, follow_up_s=0.0,
                               paging=True, max_warm=max_warm))
    assert kinds(p.decide(sig(0.0, models=[m]))) == expect


def test_paging_warm_budget():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, follow_up_s=0.0,
                               paging=True, max_warm=1))
    # One model already warm: a cold model wanting warmth is refused by
    # the cross-model budget (no action — the trigger never holds).
    acts = p.decide(sig(0.0, models=[
        mod("a", warm=True), mod("b", warm=False, wants_warm=True)]))
    assert kinds(acts) == []


def test_paging_off_by_default():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1))
    assert p.decide(
        sig(0.0, models=[mod("m", warm=False, wants_warm=True)])) == []


# -- inverse map / describe ---------------------------------------------------

def test_inverse_map_is_an_involution():
    for kind, inv in INVERSE.items():
        assert INVERSE[inv] == kind


def test_describe_counters():
    p = AutopilotPolicy(ap_cfg(hysteresis_ticks=1, cooldown_s=0.0,
                               follow_up_s=10.0, max_actions_per_window=1))
    p.decide(sig(0.0, [dom(hid=0, pressure=5.0), dom(hid=1, pressure=5.0)]))
    d = p.describe()
    assert d["actions_in_window"] == 1
    assert d["budget_deferrals_total"] == 1
    assert d["watches_open"] == 1
    assert d["rollbacks_total"] == 0


# -- the loop (no server: injected signal/actuate fns) ------------------------

def test_loop_tick_actuates_and_records():
    async def run():
        cfg = ap_cfg(hysteresis_ticks=1, cooldown_s=0.0, follow_up_s=0.0)
        ticks = iter([
            sig(0.0, [dom(pressure=5.0)]),
            sig(1.0, [dom(hid=1, pressure=5.0)]),
        ])
        acted: list[tuple[str, str]] = []

        async def actuate(a: Action) -> str:
            acted.append((a.kind, a.target))
            return "ok" if a.target == "host:0" else "error: host down"

        loop = AutopilotLoop(cfg, lambda: next(ticks), actuate)
        await loop.tick()
        await loop.tick()
        assert acted == [("scale_up", "host:0"), ("scale_up", "host:1")]
        assert loop.ticks == 2
        assert loop.actions_total == 2 and loop.errors_total == 1
        d = loop.describe()
        assert [r["outcome"] for r in d["decisions"]] \
            == ["ok", "error: host down"]
        assert d["decisions"][0]["signals"]["pressure"] == pytest.approx(5.0)

    asyncio.run(run())


def test_loop_actuator_exception_is_an_error_outcome():
    async def run():
        cfg = ap_cfg(hysteresis_ticks=1, follow_up_s=0.0)

        async def actuate(a: Action) -> str:
            raise RuntimeError("boom")

        loop = AutopilotLoop(cfg, lambda: sig(0.0, [dom(pressure=5.0)]),
                             actuate)
        await loop.tick()
        assert loop.errors_total == 1
        rec = loop.describe()["decisions"][0]
        assert rec["outcome"].startswith("error: RuntimeError")

    asyncio.run(run())


def test_loop_decision_history_is_bounded():
    async def run():
        cfg = ap_cfg(hysteresis_ticks=1, cooldown_s=0.0, follow_up_s=0.0,
                     max_actions_per_window=1000, history=4)
        t = [0.0]

        def signals():
            t[0] += 1.0
            return sig(t[0], [dom(hid=int(t[0]) % 997, pressure=5.0)])

        async def actuate(a: Action) -> str:
            return "ok"

        loop = AutopilotLoop(cfg, signals, actuate)
        for _ in range(10):
            await loop.tick()
        assert len(loop.describe()["decisions"]) == 4

    asyncio.run(run())
