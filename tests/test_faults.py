"""Chaos suite (ISSUE 1): deterministic fault injection, circuit breaking,
batch retry under load, watchdog recovery, graceful drain/SIGTERM.

Everything runs on CPU with the toy family. The HTTP tests drive real
aiohttp servers (TestServer or serve_async on an ephemeral port) and, for
the availability bound, the real load generator via faults.run_chaos —
the same harness `python -m tpuserve chaos` uses.
"""

import asyncio
import io
import os
import signal
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.config import (FaultRuleConfig, FaultsConfig, ModelConfig,
                             ServerConfig, load_config)
from tpuserve.faults import (CircuitBreaker, FaultInjected, FaultInjector,
                             Watchdog, run_chaos)
from tpuserve.obs import Metrics, percentile
from tpuserve.server import ServerState, make_app, serve_async


def toy_model_cfg(**over) -> ModelConfig:
    base = dict(name="toy", family="toy", batch_buckets=[1, 2, 4],
                deadline_ms=5.0, dtype="float32", num_classes=10,
                parallelism="single", request_timeout_ms=10_000.0)
    base.update(over)
    return ModelConfig(**base)


def toy_server_cfg(model_over=None, **over) -> ServerConfig:
    base = dict(models=[toy_model_cfg(**(model_over or {}))], decode_threads=2)
    base.update(over)
    return ServerConfig(**base)


def npy_image(seed: int = 0) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 200, (8, 8, 3), dtype=np.uint8))
    return buf.getvalue()


NPY = {"Content-Type": "application/x-npy"}


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# ---------------------------------------------------------------------------
# FaultInjector unit behavior
# ---------------------------------------------------------------------------

def test_injector_is_deterministic():
    """Same config + seed => identical firing sequence (replayable chaos)."""
    def draws(seed):
        inj = FaultInjector.single("batch_error", probability=0.3, seed=seed)
        return [inj.fire("batch_error", "m") is not None for _ in range(200)]

    a, b = draws(7), draws(7)
    assert a == b
    assert draws(8) != a
    rate = sum(a) / len(a)
    assert 0.15 < rate < 0.45  # ~0.3, loose bound


def test_injector_count_budget():
    inj = FaultInjector.single("batch_error", count=2)
    fired = [inj.fire("batch_error", "m") is not None for _ in range(10)]
    assert fired == [True, True] + [False] * 8
    assert inj.snapshot()[0]["fired"] == 2
    assert inj.snapshot()[0]["remaining"] == 0


def test_injector_model_and_kind_filters():
    inj = FaultInjector.single("batch_error", model="a")
    assert inj.fire("batch_error", "b") is None
    assert inj.fire("slow_dispatch", "a") is None
    assert inj.fire("batch_error", "a") is not None
    star = FaultInjector.single("canary_fail", model="*")
    assert star.fire("canary_fail", "anything") is not None


def test_injector_disabled_and_toggle():
    inj = FaultInjector.single("batch_error")
    inj.set_enabled(False)
    assert inj.fire("batch_error", "m") is None
    inj.set_enabled(True)
    with pytest.raises(FaultInjected):
        inj.check("batch_error", "m")


def test_injector_delay_and_metrics():
    m = Metrics()
    inj = FaultInjector.single("slow_dispatch", delay_ms=250.0, metrics=m)
    assert inj.delay_s("slow_dispatch", "m") == pytest.approx(0.25)
    assert m.counter(
        "faults_injected_total{model=m,kind=slow_dispatch}").value == 1


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultRuleConfig(kind="nope")


def test_faults_config_from_toml(tmp_path):
    p = tmp_path / "chaos.toml"
    p.write_text(
        "port = 8001\n"
        "[faults]\n"
        "enabled = true\n"
        "seed = 42\n"
        "[[faults.rule]]\n"
        'kind = "batch_error"\n'
        'model = "toy"\n'
        "probability = 0.1\n"
        "[[faults.rule]]\n"
        'kind = "slow_dispatch"\n'
        "delay_ms = 50.0\n"
        "count = 3\n")
    cfg = load_config(str(p))
    assert cfg.faults.enabled and cfg.faults.seed == 42
    assert len(cfg.faults.rules) == 2
    assert cfg.faults.rules[0].kind == "batch_error"
    assert cfg.faults.rules[0].probability == 0.1
    assert cfg.faults.rules[1].count == 3


# ---------------------------------------------------------------------------
# CircuitBreaker unit behavior
# ---------------------------------------------------------------------------

def test_breaker_opens_half_opens_closes():
    m = Metrics()
    br = CircuitBreaker("m", threshold=3, metrics=m)
    assert br.allow()
    br.record_failure()
    br.record_failure()
    assert br.allow()  # under threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()
    assert m.gauge("breaker_state{model=m}").value == 2.0
    br.probe()  # canary admitted
    assert br.state == "half_open" and not br.allow()
    br.record_failure()  # failed probe: back to open
    assert br.state == "open"
    br.probe()
    br.record_success()
    assert br.state == "closed" and br.allow()
    assert br.consecutive_errors == 0
    assert br.describe()["opened_total"] == 1


def test_breaker_threshold_zero_disables():
    br = CircuitBreaker("m", threshold=0)
    for _ in range(10):
        br.record_failure()
    assert br.allow() and br.state == "closed"


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker("m", threshold=3)
    for _ in range(2):
        br.record_failure()
    br.record_success()
    for _ in range(2):
        br.record_failure()
    assert br.state == "closed"  # never 3 consecutive


# ---------------------------------------------------------------------------
# Availability under injected faults (the acceptance bound)
# ---------------------------------------------------------------------------

def test_availability_with_10pct_batch_failures(loop):
    """10% injected batch-failure rate: >= 99% of loadgen requests still
    succeed via the one-shot retry, and the breaker never trips."""
    cfg = toy_server_cfg(faults=FaultsConfig(enabled=True, seed=1, rules=[
        FaultRuleConfig(kind="batch_error", model="toy", probability=0.10)]))
    state = ServerState(cfg)
    state.build()
    summary = loop.run_until_complete(run_chaos(
        state, "toy", duration_s=1.5, warmup_s=0.3, concurrency=8, edge=8))
    assert summary["n_ok"] > 100, summary
    assert summary["availability"] >= 0.99, summary
    fired = sum(r["fired"] for r in summary["faults"])
    assert fired > 5, summary  # chaos actually ran
    assert summary["breakers"]["toy"]["state"] == "closed"
    assert summary["breakers"]["toy"]["opened_total"] == 0


def test_reload_drill_availability(loop):
    """The ISSUE 2 acceptance bound: with reload_corrupt injected at 100%
    and :reload hammered throughout the run, every reload is rejected at
    the integrity gate, the original version keeps serving, and
    availability stays >= 99%."""
    cfg = toy_server_cfg(faults=FaultsConfig(enabled=True, seed=3, rules=[
        FaultRuleConfig(kind="reload_corrupt", model="toy")]))
    state = ServerState(cfg)
    state.build()
    summary = loop.run_until_complete(run_chaos(
        state, "toy", duration_s=1.5, warmup_s=0.3, concurrency=8, edge=8,
        drill="reload", drill_interval_s=0.1))
    assert summary["n_ok"] > 100, summary
    assert summary["availability"] >= 0.99, summary
    drill = summary["reload_drill"]
    assert drill["attempts"] >= 5, drill  # the drill actually hammered
    assert drill["ok"] == 0 and drill["rolled_back"] == 0
    assert drill["rejected"] == drill["attempts"] - drill["errors"]
    # The original version never left service; no candidate ever published.
    lc = summary["lifecycle"]["toy"]
    assert lc["live_version"] == 1
    assert all(h["status"] in ("live", "rejected") for h in lc["history"])


def test_reload_nan_drill_keeps_serving(loop):
    """Same bound for the NaN gate (reload_nan at 100%)."""
    cfg = toy_server_cfg(faults=FaultsConfig(enabled=True, seed=4, rules=[
        FaultRuleConfig(kind="reload_nan", model="toy")]))
    state = ServerState(cfg)
    state.build()
    summary = loop.run_until_complete(run_chaos(
        state, "toy", duration_s=1.0, warmup_s=0.2, concurrency=8, edge=8,
        drill="reload", drill_interval_s=0.1))
    assert summary["availability"] >= 0.99, summary
    assert summary["lifecycle"]["toy"]["live_version"] == 1
    assert summary["reload_drill"]["ok"] == 0


# ---------------------------------------------------------------------------
# Circuit breaker over HTTP: fast 503 + Retry-After, canary-driven recovery
# ---------------------------------------------------------------------------

def test_breaker_trips_fast_503_and_recovers_via_canary(loop):
    interval = 0.25
    cfg = toy_server_cfg(model_over=dict(breaker_threshold=2),
                         canary_interval_s=interval)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # Total outage below the HTTP layer: every dispatch fails.
            state.batchers["toy"].injector = FaultInjector.single("batch_error")
            for _ in range(2):  # threshold consecutive failed dispatches
                r = await client.post("/v1/models/toy:predict",
                                      data=npy_image(), headers=NPY)
                assert r.status == 500
            assert state.breakers["toy"].state == "open"

            # While open: fast shed, never a dispatch. < 5 ms p50 per the
            # acceptance bound (loopback, body never read).
            lat_ms = []
            for _ in range(40):
                t0 = time.perf_counter()
                r = await client.post("/v1/models/toy:predict",
                                      data=npy_image(), headers=NPY)
                lat_ms.append((time.perf_counter() - t0) * 1e3)
                assert r.status == 503
                assert r.headers["Retry-After"] == "1"  # ceil(canary interval)
                assert "circuit open" in (await r.json())["error"]
            assert percentile(lat_ms, 0.5) < 5.0, lat_ms
            assert state.breakers["toy"].shed_total == 40

            # Injection stops: the periodic canary (which kept riding the
            # batcher while open) closes the breaker within 2 intervals.
            state.batchers["toy"].injector = None
            t_stop = time.perf_counter()
            deadline = t_stop + 2 * interval + 0.1  # +grace for canary exec
            while time.perf_counter() < deadline:
                r = await client.post("/v1/models/toy:predict",
                                      data=npy_image(), headers=NPY)
                if r.status == 200:
                    break
                await asyncio.sleep(0.01)
            assert r.status == 200, await r.text()
            assert time.perf_counter() - t_stop <= 2 * interval + 0.1
            assert state.breakers["toy"].state == "closed"

            # /metrics carries the breaker gauge + shed counter.
            text = await (await client.get("/metrics")).text()
            assert 'breaker_state{model="toy"}' in text
            assert 'breaker_shed_total{model="toy"}' in text
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Shed responses carry Retry-After; /stats surfaces breaker + shed state
# ---------------------------------------------------------------------------

def test_429_carries_retry_after_and_stats_robustness(loop):
    cfg = toy_server_cfg(model_over=dict(max_queue=1, deadline_ms=200.0))
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            first = asyncio.ensure_future(client.post(
                "/v1/models/toy:predict", data=npy_image(), headers=NPY))
            await asyncio.sleep(0.05)  # queued, batch not yet flushed
            shed = await client.post("/v1/models/toy:predict",
                                     data=npy_image(), headers=NPY)
            assert shed.status == 429
            assert shed.headers["Retry-After"] == "1"
            assert (await (await first).json())["top_k"]

            stats = await (await client.get("/stats")).json()
            rob = stats["robustness"]
            assert rob["draining"] is False
            assert rob["breakers"]["toy"]["state"] == "closed"
            assert "shed_total" in rob["breakers"]["toy"]
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Watchdog: dead group loop is detected and revived
# ---------------------------------------------------------------------------

def test_watchdog_revives_killed_group_loop(loop):
    cfg = toy_server_cfg(watchdog_interval_s=0.05)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            b = state.batchers["toy"]
            # Arm a one-shot loop kill: it fires at the top of the NEXT
            # accumulation iteration, i.e. right after this batch flushes.
            b.injector = FaultInjector.single("kill_group_loop", count=1)
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY)
            assert r.status == 200
            await asyncio.sleep(0.02)
            (task,) = b._tasks.values()
            assert task.done()
            assert isinstance(task.exception(), FaultInjected)

            await asyncio.sleep(0.2)  # >= a few watchdog sweeps
            (task,) = b._tasks.values()
            assert not task.done()  # revived
            restarts = state.metrics.counter(
                "watchdog_restarts_total{model=toy,component=group_loop}")
            assert restarts.value >= 1
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY)
            assert r.status == 200  # serving again through the revived loop
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_watchdog_sweep_unit():
    """Sweeps aggregate restart counts into the labeled counter; a raising
    sweep is contained."""
    m = Metrics()
    wd = Watchdog(1.0, m)
    wd.register("a", "group_loop", lambda: 2)
    wd.register("a", "worker", lambda: 0)

    def boom() -> int:
        raise RuntimeError("sweep failed")

    wd.register("b", "group_loop", boom)
    assert wd.sweep() == 2
    assert m.counter(
        "watchdog_restarts_total{model=a,component=group_loop}").value == 2


# ---------------------------------------------------------------------------
# Graceful drain + SIGTERM: zero accepted requests dropped
# ---------------------------------------------------------------------------

def test_drain_completes_accepted_rejects_new(loop):
    cfg = toy_server_cfg(
        faults=FaultsConfig(enabled=True, rules=[
            FaultRuleConfig(kind="slow_dispatch", delay_ms=150.0)]),
        drain_timeout_s=5.0)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            inflight = [asyncio.ensure_future(client.post(
                "/v1/models/toy:predict", data=npy_image(i), headers=NPY))
                for i in range(5)]
            await asyncio.sleep(0.05)  # all accepted, dispatch mid-sleep
            drain_task = asyncio.ensure_future(state.drain())
            await asyncio.sleep(0)  # draining flag set synchronously

            late = await client.post("/v1/models/toy:predict",
                                     data=npy_image(), headers=NPY)
            assert late.status == 503
            assert late.headers["Retry-After"] == "1"
            assert "draining" in (await late.json())["error"]
            health = await client.get("/healthz")
            assert health.status == 503
            assert (await health.json())["status"] == "draining"
            stats = await (await client.get("/stats")).json()
            assert stats["robustness"]["draining"] is True

            for resp in await asyncio.gather(*inflight):
                assert resp.status == 200  # every accepted request finished
            assert await drain_task is True
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_sigterm_drains_under_load():
    """End-to-end serve_async: SIGTERM during load completes every accepted
    request (with responses), then the server exits cleanly."""
    import aiohttp

    cfg = toy_server_cfg(
        host="127.0.0.1", port=0, startup_canary=False,
        faults=FaultsConfig(enabled=True, rules=[
            FaultRuleConfig(kind="slow_dispatch", delay_ms=150.0)]),
        drain_timeout_s=10.0)
    state = ServerState(cfg)
    state.build()
    loop = asyncio.new_event_loop()

    async def go():
        ready = asyncio.Event()
        server = asyncio.ensure_future(serve_async(state, ready=ready))
        await ready.wait()
        port = state.serving_addresses[0][1]
        url = f"http://127.0.0.1:{port}/v1/models/toy:predict"
        async with aiohttp.ClientSession() as session:

            async def one(i: int):
                async with session.post(url, data=npy_image(i),
                                        headers=NPY) as resp:
                    return resp.status, await resp.json()

            reqs = [asyncio.ensure_future(one(i)) for i in range(4)]
            await asyncio.sleep(0.05)  # accepted, still in flight
            os.kill(os.getpid(), signal.SIGTERM)
            results = await asyncio.gather(*reqs)
        for status, body in results:
            assert status == 200, body
            assert "top_k" in body
        await server  # clean exit, no hang
        assert state.draining

    try:
        loop.run_until_complete(go())
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# Below-the-batcher faults: runtime device errors are retried too
# ---------------------------------------------------------------------------

def test_device_error_below_batcher_retried(loop):
    cfg = toy_server_cfg(faults=FaultsConfig(enabled=True, rules=[
        FaultRuleConfig(kind="device_error", model="toy", count=1)]))
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY)
            assert r.status == 200, await r.text()  # retry absorbed it
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_slow_compute_below_batcher_still_serves(loop):
    """slow_compute injects a sleep inside ModelRuntime.dispatch — on a
    stage-executor thread, below the batcher. The request must still answer
    200, just slower, and the injected delay must show up in the dispatch
    wall time (the fault existed since ISSUE 1 but had no test: surfaced by
    `tpuserve lint` TPS403)."""
    cfg = toy_server_cfg(startup_canary=False,
                         faults=FaultsConfig(enabled=True, rules=[
                             FaultRuleConfig(kind="slow_compute", model="toy",
                                             count=1, delay_ms=300.0)]))
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            t0 = time.perf_counter()
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY)
            elapsed = time.perf_counter() - t0
            assert r.status == 200, await r.text()
            assert elapsed >= 0.3, elapsed  # the injected sleep was real
            snap = state.injector.snapshot()
            fired = [r for r in snap if r["kind"] == "slow_compute"]
            assert fired and fired[0]["fired"] == 1, snap
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_decode_corrupt_maps_to_400(loop):
    cfg = toy_server_cfg(faults=FaultsConfig(enabled=True, rules=[
        FaultRuleConfig(kind="decode_corrupt", count=1)]))
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY)
            assert r.status == 400
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY)
            assert r.status == 200  # count budget spent
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Deferred pool: worker death is contained, retried, and swept
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_deferred_worker_death_retried_and_swept():
    import concurrent.futures as cf

    from tpuserve.batcher import ModelBatcher
    from tpuserve.deferred import DeferredPool
    from tpuserve.models import build

    cfg = toy_model_cfg(batch_buckets=[1, 2], session_mode="recycle",
                        relay_workers=2, relay_epoch_images=64,
                        relay_epoch_ms=300.0, request_timeout_ms=30_000.0)
    model = build(cfg)
    pool = DeferredPool(cfg, "", model,
                        injector=FaultInjector.single("worker_death", count=1))
    pool.prewarm()

    async def go():
        await pool.start()
        metrics = Metrics()
        tp = cf.ThreadPoolExecutor(max_workers=4)
        b = ModelBatcher(model, pool, metrics, tp)
        await b.start()
        item = np.random.default_rng(0).integers(0, 200, (8, 8, 3),
                                                 dtype=np.uint8)
        # First request lands on worker A; the second enqueue kills A
        # (chaos), failing the first batch's future -> batcher retries it
        # onto the replacement worker. Both clients still get results.
        f1 = b.submit(item)
        await asyncio.sleep(0.05)
        f2 = b.submit(item)
        r1, r2 = await asyncio.wait_for(asyncio.gather(f1, f2), timeout=60)
        assert "top_k" in r1 and "top_k" in r2
        assert metrics.counter("batch_retries_total{model=toy}").value >= 1
        pool.watchdog_sweep()  # reaps the killed worker handle
        assert all(w.proc.is_alive() or w.retired or not w.pending
                   for w in pool._workers)
        await b.stop()
        await pool.stop()
        tp.shutdown(wait=False)

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()
