"""Router/worker process split (ISSUE 8): real multi-process fleets.

Three layers of coverage, all against REAL worker processes (spawned, own
PJRT sessions, loopback HTTP) — the process boundary is the point, so
nothing here is mocked across it:

- single-process drain sequencing + live Retry-After derivation (the
  in-process satellites the cross-process drain builds on);
- a module-scoped router fleet (2 workers, chaos-armed models) proving
  deadline propagation across the boundary (504 at the same absolute
  instant whether the request dies in the router, on the wire, or inside a
  worker), retry-never-extends-deadline, no-double-execution after a
  definitive answer, hedging over a wedged worker, the worker_slow fault,
  the atomic reload fan-out, and the router-owned cache;
- a function-scoped fleet where worker_crash kills every worker
  (degradation to 503 + live Retry-After, then supervised respawn back to
  health).

No pytest-asyncio in the image: a module-level event loop drives
everything explicitly (the test_http idiom).
"""

import asyncio
import io
import signal
import time

import numpy as np
import pytest

from tpuserve.config import (
    FaultRuleConfig,
    FaultsConfig,
    ModelConfig,
    RouterConfig,
    ServerConfig,
)

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning")

NPY = "application/x-npy"


def npy(seed: int = 0, edge: int = 8) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (edge, edge, 3), dtype=np.uint8))
    return buf.getvalue()


def _toy(name: str, **kw) -> ModelConfig:
    base = dict(family="toy", batch_buckets=[1, 2], deadline_ms=2.0,
                dtype="float32", num_classes=10, parallelism="single",
                request_timeout_ms=10_000.0, wire_size=8, max_inflight=2)
    base.update(kw)
    return ModelConfig(name=name, **base)


def _parse_metrics(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        k, v = line.rsplit(" ", 1)
        try:
            out[k] = float(v)
        except ValueError:
            pass
    return out


@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# ---------------------------------------------------------------------------
# Single-process satellites
# ---------------------------------------------------------------------------

def test_drain_stops_revival_machinery_before_flush(loop):
    """SIGTERM sequencing (ISSUE 8 satellite): drain() must stop the
    watchdog and the periodic canary BEFORE quiescing the batchers, so a
    sweep can never revive a group loop (or background-respawn a deferred
    worker) that the shutdown is intentionally stopping, and no canary can
    inject new work after admission closed."""
    from tpuserve.server import ServerState

    cfg = ServerConfig(models=[_toy("toy")], decode_threads=2,
                       startup_canary=False, canary_interval_s=0.5,
                       watchdog_interval_s=0.1)
    state = ServerState(cfg)
    state.build()

    async def go():
        await state.start()
        assert state._canary_task is not None
        assert state.watchdog._task is not None
        sweeps = []
        state.watchdog.register("probe", "probe",
                                lambda: sweeps.append(1) or 0)
        ok = await state.drain()
        assert ok
        # Both revival mechanisms are gone by the time drain() returns —
        # not merely "will be stopped later in stop()".
        assert state.watchdog._task is None
        assert state._canary_task is None
        n = len(sweeps)
        await asyncio.sleep(0.35)  # > 3 watchdog intervals
        assert len(sweeps) == n, "watchdog swept after drain() returned"
        assert state.draining
        await state.stop()

    loop.run_until_complete(go())


def test_retry_after_derived_from_live_state(loop):
    """429/503 Retry-After hints derive from live state (ISSUE 8
    satellite): queue-full 429s from the batcher's queue-clear estimate,
    breaker 503s from the next periodic-canary (recovery probe) ETA."""
    from tpuserve.server import ServerState

    cfg = ServerConfig(models=[], canary_interval_s=10.0)
    state = ServerState(cfg)

    class StubBatcher:
        def __init__(self, est):
            self.est = est

        def estimate_clear_s(self):
            return self.est

    state.batchers["m"] = StubBatcher(4.2)
    assert state.queue_retry_after("m") == 5  # ceil of the live estimate
    state.batchers["m"] = StubBatcher(9999.0)
    assert state.queue_retry_after("m") == 30  # clamped
    state.batchers["m"] = StubBatcher(None)
    assert state.queue_retry_after("m") == 1  # fallback: shed_retry_after_s

    # Breaker hint = time to the NEXT canary probe, not a constant.
    state._next_canary_at = time.monotonic() + 3.4
    assert state.breaker_retry_after("m") in (3, 4)
    state._next_canary_at = time.monotonic() - 1.0
    assert state.breaker_retry_after("m") == 1  # probe due now
    state._next_canary_at = None
    assert state.breaker_retry_after("m") == 10  # loop not armed yet


def test_estimate_clear_s_from_ewma(loop):
    """ModelBatcher.estimate_clear_s: pending over the best demonstrated
    bucket rate; None with no EWMA or an empty queue."""
    from tpuserve.server import ServerState

    cfg = ServerConfig(models=[_toy("toy")], decode_threads=2,
                       startup_canary=False)
    state = ServerState(cfg)
    state.build()

    async def go():
        await state.start()
        b = state.batchers["toy"]
        assert b.estimate_clear_s() is None  # empty queue
        b._ewma_ms[(2,)] = 100.0  # 2 items / 100 ms -> 20 items/s
        b._pending = 10
        est = b.estimate_clear_s()
        assert est == pytest.approx(0.5)
        b._pending = 0
        assert b.estimate_clear_s() is None
        await state.stop()

    loop.run_until_complete(go())


def test_worker_config_derivation_and_recycle_rejection():
    """Worker configs derive once from the deployment config: loopback
    bind, router recursion and the router-owned cache forced off; recycle
    mode (its own process split, incompatible with daemonic workers) is
    rejected up front."""
    from tpuserve.workerproc.worker import worker_config

    cfg = ServerConfig(models=[_toy("toy")],
                       router=RouterConfig(enabled=True, workers=2))
    cfg.cache.enabled = True
    wcfg = worker_config(cfg, 1)
    assert wcfg.host == "127.0.0.1" and wcfg.port == 0
    assert wcfg.router.enabled is False
    assert wcfg.cache.enabled is False
    assert cfg.cache.enabled is True  # the deployment config is untouched

    cfg.worker.port_base = 9200
    assert worker_config(cfg, 3).port == 9203
    cfg.worker.drain_timeout_s = 2.0
    assert worker_config(cfg, 0).drain_timeout_s == 2.0

    bad = ServerConfig(models=[_toy("rc", session_mode="recycle")],
                       router=RouterConfig(enabled=True))
    with pytest.raises(ValueError, match="recycle"):
        worker_config(bad, 0)


# ---------------------------------------------------------------------------
# The router fleet (module-scoped: 2 real worker processes)
# ---------------------------------------------------------------------------

def _fleet_cfg() -> ServerConfig:
    return ServerConfig(
        decode_threads=2,
        startup_canary=False,
        # Short drain: the toyhang test deliberately leaves wedged handlers
        # inside the workers, and the supervisor's SIGKILL-after-budget is
        # exactly how a real deployment evicts them — just don't wait the
        # production 30 s for it in a test teardown.
        drain_timeout_s=3.0,
        router=RouterConfig(enabled=True, workers=2, retry_max=2,
                            hedge_ms=150.0, health_interval_s=0.2,
                            unhealthy_after=2, respawn_initial_s=0.3,
                            respawn_max_s=2.0),
        models=[
            _toy("toy"),
            # slow_compute fires INSIDE the worker's runtime: the request
            # must 504 at its router-stamped deadline, not at 600 ms.
            _toy("toyslow"),
            # worker_hang wedges the worker's handler: no response ever.
            _toy("toyhang"),
            # worker_slow delays the worker's handler by delay_ms.
            _toy("toylag"),
            # batch_error + no worker-side retry: every execution is a
            # definitive 500 (the no-double-execution probe).
            _toy("toyerr", batch_retry=False, retry_split=False,
                 breaker_threshold=0),
            # Same, but with a router breaker armed (threshold 2).
            _toy("toytrip", batch_retry=False, retry_split=False,
                 breaker_threshold=2, breaker_retry_after_s=1.0),
        ],
        faults=FaultsConfig(enabled=True, seed=7, rules=[
            FaultRuleConfig(kind="slow_compute", model="toyslow",
                            delay_ms=600.0),
            FaultRuleConfig(kind="worker_hang", model="toyhang"),
            FaultRuleConfig(kind="worker_slow", model="toylag",
                            delay_ms=300.0),
            FaultRuleConfig(kind="batch_error", model="toyerr"),
            FaultRuleConfig(kind="batch_error", model="toytrip"),
        ]),
    )


@pytest.fixture(scope="module")
def fleet(loop):
    import aiohttp
    from aiohttp import web

    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg = _fleet_cfg()
    cfg.cache.enabled = True
    cfg.cache.capacity = 64
    state = RouterState(cfg)
    runner = web.AppRunner(make_router_app(state), access_log=None)

    async def setup():
        await runner.setup()  # on_startup spawns the fleet
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return aiohttp.ClientSession()

    session = loop.run_until_complete(setup())
    base = f"http://127.0.0.1:{runner.addresses[0][1]}"

    def run(coro):
        return loop.run_until_complete(coro)

    yield run, session, base, state

    async def teardown():
        await session.close()
        await runner.cleanup()

    loop.run_until_complete(teardown())


async def _post(session, base, model, body, verb="classify", timeout_ms=None,
                total=30.0):
    import aiohttp

    params = {"timeout_ms": str(timeout_ms)} if timeout_ms else None
    async with session.post(f"{base}/v1/models/{model}:{verb}", data=body,
                            params=params,
                            headers={"Content-Type": NPY},
                            timeout=aiohttp.ClientTimeout(total=total)) as r:
        return r.status, await r.read(), dict(r.headers)


async def _worker_metric_sum(session, base, key, n=2) -> float:
    """Sum one Prometheus metric across every worker's own /metrics."""
    total = 0.0
    for i in range(n):
        async with session.get(f"{base}/workers/{i}/metrics") as r:
            assert r.status == 200, await r.text()
            total += _parse_metrics(await r.text()).get(key, 0.0)
    return total


def test_router_predict_and_introspection(fleet):
    run, session, base, state = fleet

    async def go():
        status, body, _ = await _post(session, base, "toy", npy(1))
        assert status == 200, body
        assert b"top_k" in body
        async with session.get(f"{base}/healthz") as r:
            health = await r.json()
            assert r.status == 200 and health["status"] == "ok"
        async with session.get(f"{base}/stats") as r:
            stats = await r.json()
        assert stats["workers"]["healthy"] == 2
        assert stats["workers"]["configured"] == 2
        assert {row["state"] for row in stats["workers"]["workers"]} == {"ready"}
        assert stats["router"]["generations"]["toy"] == 1
        async with session.get(f"{base}/metrics") as r:
            m = _parse_metrics(await r.text())
        assert m.get('worker_up{worker="0"}') == 1.0
        assert m.get('worker_up{worker="1"}') == 1.0
        # The workers really are separate processes serving real models.
        async with session.get(f"{base}/workers/1/stats") as r:
            wstats = await r.json()
        assert "pipeline" in wstats

    run(go())


def test_router_cache_hit_and_single_execution(fleet):
    """The PR-5 cache lives in the ROUTER: a byte-identical re-upload is
    answered without any worker executing a second time."""
    run, session, base, state = fleet

    async def go():
        body = npy(42)
        before = await _worker_metric_sum(
            session, base, 'requests_total{model="toy"}')
        s1, b1, _ = await _post(session, base, "toy", body)
        s2, b2, _ = await _post(session, base, "toy", body)
        assert s1 == 200 and s2 == 200
        assert b1 == b2  # the hit serves the exact cached bytes
        after = await _worker_metric_sum(
            session, base, 'requests_total{model="toy"}')
        assert after - before == 1, "cache hit must not reach a worker"
        async with session.get(f"{base}/stats") as r:
            stats = await r.json()
        assert stats["cache"]["toy"]["hits"] >= 1

    run(go())


def test_priority_relayed_end_to_end_and_not_in_cache_key(fleet):
    """ISSUE 10 satellite: X-Priority rides header -> worker -> batcher
    (the worker's queue-wait split records the relayed class), and the
    router's wire cache key NEVER sees it — same bytes, same entry,
    whatever the priority."""
    run, session, base, state = fleet

    async def go():
        body = npy(777)
        qkey = 'queue_wait_ms_count{model="toy",priority="batch"}'
        before_q = await _worker_metric_sum(session, base, qkey)
        before_req = await _worker_metric_sum(
            session, base, 'requests_total{model="toy"}')
        async with session.post(
                f"{base}/v1/models/toy:classify", data=body,
                headers={"Content-Type": NPY, "X-Priority": "batch"}) as r:
            assert r.status == 200, await r.text()
            first = await r.read()
        after_q = await _worker_metric_sum(session, base, qkey)
        assert after_q - before_q == 1, \
            "relayed X-Priority must reach the worker's batcher split"
        # Same bytes, DIFFERENT priority: must hit the router cache — no
        # second worker execution, byte-identical answer.
        async with session.post(
                f"{base}/v1/models/toy:classify", data=body,
                headers={"Content-Type": NPY,
                         "X-Priority": "interactive"}) as r:
            assert r.status == 200
            assert await r.read() == first
        after_req = await _worker_metric_sum(
            session, base, 'requests_total{model="toy"}')
        assert after_req - before_req == 1, \
            "priority must not enter the cache key (same bytes, same key)"

    run(go())


def test_router_records_worker_shed_reason():
    """The router remembers the machine-readable `reason` workers answer
    on scheduler sheds, and carries it on its own breaker 503s."""
    from tpuserve.workerproc.router import RouterState, _Answer

    cfg = ServerConfig(models=[_toy("toy")],
                       router=RouterConfig(enabled=True, workers=1))
    state = RouterState(cfg)
    state.note_shed_reason("toy", _Answer(
        503, "application/json",
        b'{"error": "warming", "reason": "model_warming"}', None))
    assert state.last_shed_reason["toy"] == "model_warming"
    # Non-shed statuses and junk bodies never overwrite it.
    state.note_shed_reason("toy", _Answer(200, "application/json",
                                          b'{"reason": "nope"}', None))
    state.note_shed_reason("toy", _Answer(503, "text/plain",
                                          b"not json", None))
    assert state.last_shed_reason["toy"] == "model_warming"


def test_deadline_expires_inside_worker(fleet):
    """Deadline propagation (ISSUE 8 satellite): the router stamps the
    absolute deadline at admission and forwards the remaining budget; a
    request that dies inside a worker (600 ms injected compute) 504s at
    ~its 250 ms deadline — not after the slow compute, and not stretched
    by the hedge that fires meanwhile."""
    run, session, base, state = fleet

    async def go():
        t0 = time.perf_counter()
        status, body, _ = await _post(session, base, "toyslow", npy(2),
                                      timeout_ms=250)
        elapsed = time.perf_counter() - t0
        assert status == 504, body
        assert 0.2 <= elapsed < 1.5, elapsed

    run(go())


def test_deadline_expires_on_wire_and_retry_never_extends(fleet):
    """Both workers SIGSTOPped: attempts connect but never answer, so the
    request expires 'on the wire'. The router hedges and retries within
    the budget, and the answer still lands at the stamped deadline (+ the
    backstop grace) — re-dispatch never extends it."""
    run, session, base, state = fleet
    pids = [h.pid for h in state.supervisor.slots if h is not None]
    assert len(pids) == 2

    async def go():
        for pid in pids:
            import os

            os.kill(pid, signal.SIGSTOP)
        try:
            t0 = time.perf_counter()
            status, body, _ = await _post(session, base, "toy", npy(3),
                                          timeout_ms=400)
            elapsed = time.perf_counter() - t0
            assert status == 504, body
            # deadline 0.4 s + 0.25 s grace + scheduling slack; far below
            # any retry-stretched horizon.
            assert 0.35 <= elapsed < 1.5, elapsed
        finally:
            import os

            for pid in pids:
                os.kill(pid, signal.SIGCONT)
        # Health probes may have marked the stopped workers unhealthy;
        # wait for the fleet to report fully healthy again.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            async with session.get(f"{base}/healthz") as r:
                health = await r.json()
            if health["status"] == "ok":
                break
            await asyncio.sleep(0.1)
        assert health["status"] == "ok", health

    run(go())


def test_no_double_execution_after_definitive_answer(fleet):
    """A 500 from a worker is DEFINITIVE — the work executed and failed.
    The router must relay it without re-dispatching: across both workers,
    exactly one execution is observed, and the router retry counter does
    not move."""
    run, session, base, state = fleet

    async def go():
        key = 'requests_total{model="toyerr"}'
        before = await _worker_metric_sum(session, base, key)
        async with session.get(f"{base}/metrics") as r:
            retries_before = _parse_metrics(await r.text()).get(
                'router_retries_total{model="toyerr"}', 0.0)
        status, body, _ = await _post(session, base, "toyerr", npy(4))
        assert status == 500, body
        after = await _worker_metric_sum(session, base, key)
        assert after - before == 1, "definitive 500 was re-dispatched"
        async with session.get(f"{base}/metrics") as r:
            retries_after = _parse_metrics(await r.text()).get(
                'router_retries_total{model="toyerr"}', 0.0)
        assert retries_after == retries_before

    run(go())


def test_worker_hang_hedged_then_504_at_deadline(fleet):
    """worker_hang wedges the handling worker silently. The hedge races a
    duplicate on the other worker after hedge_ms; with both wedged (the
    rule is armed in every worker) the request still 504s AT its deadline."""
    run, session, base, state = fleet

    async def go():
        async with session.get(f"{base}/metrics") as r:
            hedges_before = _parse_metrics(await r.text()).get(
                'router_hedges_total{model="toyhang"}', 0.0)
        t0 = time.perf_counter()
        status, body, _ = await _post(session, base, "toyhang", npy(5),
                                      timeout_ms=600)
        elapsed = time.perf_counter() - t0
        assert status == 504, body
        assert 0.55 <= elapsed < 2.0, elapsed
        async with session.get(f"{base}/metrics") as r:
            hedges_after = _parse_metrics(await r.text()).get(
                'router_hedges_total{model="toyhang"}', 0.0)
        assert hedges_after == hedges_before + 1

    run(go())


def test_worker_slow_fault_delays_but_serves(fleet):
    """worker_slow injects latency inside the worker process; within the
    deadline the request still answers."""
    run, session, base, state = fleet

    async def go():
        t0 = time.perf_counter()
        status, body, _ = await _post(session, base, "toylag", npy(6),
                                      timeout_ms=5000)
        elapsed = time.perf_counter() - t0
        assert status == 200, body
        assert elapsed >= 0.3, elapsed  # the injected delay really applied

    run(go())


def test_router_breaker_sheds_with_live_probe_eta(fleet):
    """Router-side breaker (threshold 2 on toytrip): consecutive definitive
    500s trip it; shed 503s carry the half-open probe ETA as Retry-After,
    and one request per interval is let through as the probe."""
    run, session, base, state = fleet

    async def go():
        for _ in range(3):
            status, body, _ = await _post(session, base, "toytrip", npy(7))
            assert status in (500, 503), body
        # Tripped + probe consumed: the next request sheds fast.
        status, body, headers = await _post(session, base, "toytrip", npy(7))
        assert status == 503, body
        assert b"circuit open" in body
        assert int(headers["Retry-After"]) >= 1
        assert state.breakers["toytrip"].state in ("open", "half_open")

    run(go())


def test_reload_fans_out_atomically(fleet):
    """Admin :reload reaches EVERY worker; success bumps the router cache
    generation (atomic fleet-wide invalidation) and the fleet reports one
    consistent version."""
    run, session, base, state = fleet

    async def go():
        body = npy(77)
        s1, _, _ = await _post(session, base, "toy", body)  # populate cache
        assert s1 == 200
        gen_before = state.generations["toy"]
        async with session.post(f"{base}/admin/models/toy:reload") as r:
            info = await r.json()
            assert r.status == 200, info
        assert info["fleet_consistent"] is True
        assert len(info["workers"]) == 2
        versions = {w["version"] for w in info["workers"].values()}
        assert len(versions) == 1
        assert state.generations["toy"] == gen_before + 1
        async with session.get(f"{base}/stats") as r:
            stats = await r.json()
        assert stats["cache"]["toy"]["entries"] == 0  # invalidated
        # Per-worker versions agree over the fan-out endpoint too.
        async with session.get(f"{base}/admin/models/toy/versions") as r:
            vers = await r.json()
            assert r.status == 200
        live = {w["live_version"] for w in vers["workers"].values()}
        assert len(live) == 1

    run(go())


def test_router_drain_sheds_with_retry_after(fleet):
    run, session, base, state = fleet

    async def go():
        state.begin_drain()
        try:
            status, body, headers = await _post(session, base, "toy", npy(8))
            assert status == 503 and b"draining" in body
            assert int(headers["Retry-After"]) >= 1
            async with session.get(f"{base}/healthz") as r:
                assert r.status == 503
                assert (await r.json())["status"] == "draining"
        finally:
            state.draining = False

    run(go())


# ---------------------------------------------------------------------------
# worker_crash: degradation and supervised recovery (own fleet — destructive)
# ---------------------------------------------------------------------------

def test_worker_crash_degrades_then_respawns(loop):
    """worker_crash os._exits a worker mid-request (native-crash stand-in).
    With every worker down the front door answers fast 503s whose
    Retry-After comes from the live respawn backoff — lost capacity, never
    lost availability (no hang, no connection error) — and the supervisor
    respawns the fleet back to health within its backoff budget."""
    import aiohttp
    from aiohttp import web

    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg = ServerConfig(
        decode_threads=2, startup_canary=False, drain_timeout_s=3.0,
        router=RouterConfig(enabled=True, workers=2, retry_max=2,
                            health_interval_s=0.2, unhealthy_after=2,
                            respawn_initial_s=0.3, respawn_max_s=2.0),
        models=[
            _toy("toy"),
            _toy("toyboom"),
        ],
        faults=FaultsConfig(enabled=True, rules=[
            # One shot per PROCESS: the first toyboom request each worker
            # sees kills that worker.
            FaultRuleConfig(kind="worker_crash", model="toyboom", count=1),
        ]),
    )
    state = RouterState(cfg)
    runner = web.AppRunner(make_router_app(state), access_log=None)

    async def go():
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        base = f"http://127.0.0.1:{runner.addresses[0][1]}"
        async with aiohttp.ClientSession() as session:
            try:
                # Crashes worker 1 (transport error), retries onto worker 2,
                # which crashes too: the whole fleet is down. The answer
                # must still be a FAST, clean 503.
                t0 = time.perf_counter()
                status, body, headers = await _post(
                    session, base, "toyboom", npy(9), total=30.0)
                elapsed = time.perf_counter() - t0
                assert status == 503, body
                assert int(headers["Retry-After"]) >= 1
                assert elapsed < 10.0, elapsed
                # Detection is asynchronous (health probes / watchdog
                # sweep), so poll rather than assert instantly.
                deadline = time.monotonic() + 5.0
                while (state.supervisor.deaths_total < 2
                       and time.monotonic() < deadline):
                    await asyncio.sleep(0.1)
                assert state.supervisor.deaths_total >= 2

                # Supervised recovery: both slots respawn (backoff 0.3 s +
                # boot) and the fleet serves again.
                deadline = time.monotonic() + 90.0
                while time.monotonic() < deadline:
                    async with session.get(f"{base}/healthz") as r:
                        health = await r.json()
                    if r.status == 200 and health["status"] == "ok":
                        break
                    await asyncio.sleep(0.2)
                assert health["status"] == "ok", health
                status, body, _ = await _post(session, base, "toy", npy(10))
                assert status == 200, body

                async with session.get(f"{base}/metrics") as r:
                    m = _parse_metrics(await r.text())
                respawns = (m.get('worker_respawns_total{worker="0"}', 0.0)
                            + m.get('worker_respawns_total{worker="1"}', 0.0))
                assert respawns >= 2, m
            finally:
                await runner.cleanup()

    loop.run_until_complete(go())


def test_trace_propagates_across_router_worker_hop(fleet):
    """ISSUE 12: one trace id end-to-end — the response header, the router
    /debug/slow reservoir, and a stitched /debug/trace whose span tree
    crosses the process boundary (router spans on pid 0, worker spans on
    pid = worker id + 1, the worker's root parented under the router's
    attempt span)."""
    import json

    run, session, base, state = fleet

    async def go():
        # toylag's worker_slow fault (300 ms) makes this the slowest toylag
        # request by far — guaranteed into both recorders' slow reservoirs.
        status, body, headers = await _post(session, base, "toylag", npy(91))
        assert status == 200, body
        tid = headers["X-Trace-Id"]
        assert len(tid) == 32 and int(tid, 16) >= 0

        async with session.get(f"{base}/debug/slow") as r:
            assert r.status == 200
            dump = await r.json()
        lag_ids = {rec["trace_id"] for rec in dump["slow"].get("toylag", [])}
        assert tid in lag_ids, sorted(dump["slow"])

        async with session.get(f"{base}/debug/trace?trace_id={tid}") as r:
            assert r.status == 200
            data = json.loads(await r.text())
        evs = data["traceEvents"]
        assert evs and all(e["args"]["trace_id"] == tid for e in evs)
        by_pid: dict = {}
        for e in evs:
            by_pid.setdefault(e["pid"], set()).add(e["name"])
        # Router lane: the root request span + at least one relay attempt.
        assert {"request", "attempt"} <= by_pid[0], by_pid
        # Worker lane(s): the full single-process serving tree.
        worker_pids = [p for p in by_pid if p >= 1]
        assert worker_pids, by_pid
        worker_names = set().union(*(by_pid[p] for p in worker_pids))
        assert {"request", "body_read", "queue", "compute"} <= worker_names

        # Raw record form: the worker's root span parents under the
        # router's attempt span (the X-Parent-Span relay).
        async with session.get(
                f"{base}/debug/trace?trace_id={tid}&format=record") as r:
            rec = await r.json()
        spans = rec["spans"]
        attempts = {s["span_id"] for s in spans if s["name"] == "attempt"}
        worker_roots = [s for s in spans
                        if s["name"] == "request" and s["pid"] >= 1]
        assert worker_roots
        assert all(s["parent_id"] in attempts for s in worker_roots)
        assert "router" in rec["sources"] and len(rec["sources"]) >= 2

    run(go())


def test_router_error_bodies_carry_trace_id(fleet):
    """Error paths across the tier: a router-side 404 and a worker-side
    504 both answer with trace_id in the JSON body matching X-Trace-Id —
    and the relayed 504's id is the ONE id the router stamped (the worker
    adopted it, never minted its own)."""
    import json

    run, session, base, state = fleet

    async def go():
        status, body, headers = await _post(session, base, "ghost", npy(1))
        assert status == 404
        js = json.loads(body)
        assert js["trace_id"] == headers["X-Trace-Id"]

        # slow_compute (600 ms) vs a 250 ms deadline: 504s inside the
        # worker; the body the client sees was built by the WORKER with
        # the router-minted trace id.
        status, body, headers = await _post(session, base, "toyslow",
                                            npy(92), timeout_ms=250)
        assert status == 504, body
        js = json.loads(body)
        assert js.get("trace_id") == headers["X-Trace-Id"], js
        # Errored request retained by the router's flight recorder.
        assert state.recorder.get(headers["X-Trace-Id"]) is not None

    run(go())


# ---------------------------------------------------------------------------
# Stream termination reasons (TPS404 contract)
# ---------------------------------------------------------------------------

def test_stream_error_terminal_encodings():
    """_stream_error_bytes builds the terminal the router appends when the
    worker no longer can — SSE error event for text streams, a KIND_EVENT
    frame for binary — naming the reason ("idle_timeout",
    "upstream_error") that router_stream_terminated_total keys on."""
    import json

    from tpuserve import frame
    from tpuserve.workerproc.router import _stream_error_bytes

    sse = _stream_error_bytes("text/event-stream", "idle_timeout",
                              "no bytes for 5000 ms")
    assert sse.startswith(b"event: error\ndata: ")
    assert sse.endswith(b"\n\n")
    assert json.loads(sse.split(b"data: ", 1)[1]) == {
        "error": "idle_timeout", "message": "no bytes for 5000 ms"}

    raw = _stream_error_bytes(frame.CONTENT_TYPE, "upstream_error",
                              "worker died")
    events = list(frame.StreamFrameReader().feed(raw))
    assert len(events) == 1
    payload = json.loads(events[0][1])
    assert payload == {"type": "error", "error": "upstream_error",
                       "message": "worker died"}


def test_router_termination_vocabulary_is_closed():
    """The router's stream-termination counter is guarded by the closed
    ROUTER_STREAM_REASONS vocabulary: "client_disconnect" and friends
    tick; an off-list reason raises instead of minting a new label."""
    import types

    from tpuserve.obs import ROUTER_STREAM_REASONS, Metrics
    from tpuserve.workerproc.router import RouterState

    dummy = types.SimpleNamespace(metrics=Metrics())
    for reason in ROUTER_STREAM_REASONS:
        RouterState._count_stream_termination(dummy, "toy", reason)
    assert dummy.metrics.counter(
        "router_stream_terminated_total{model=toy,"
        "reason=client_disconnect}").value == 1
    with pytest.raises(ValueError, match="unknown stream-termination"):
        RouterState._count_stream_termination(dummy, "toy", "freestyle")
