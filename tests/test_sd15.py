"""Stable Diffusion 1.5 (config 5): tiny-variant txt2img end-to-end,
determinism, padded-lane invariance, DDIM schedule math, full-size parameter
parity with the published model. VERDICT.md r2 item 8; SURVEY.md §3e."""

import asyncio
import io

import jax
import numpy as np
import pytest

from tpuserve.config import ModelConfig, ServerConfig
from tpuserve.models import build
from tpuserve.models.sd15 import MAX_TOKENS, ddim_schedule

pytestmark = pytest.mark.slow

TINY = dict(steps=3, guidance=5.0, vocab_size=512,
            text_layers=1, text_d_model=32, text_heads=2,
            unet_ch=16, unet_mults=[1, 2], unet_res=1, unet_attn_levels=[0],
            unet_heads=2, vae_ch=16, vae_mults=[1, 2])


def sd_cfg(**over) -> ModelConfig:
    base = dict(
        name="sd", family="sd15", batch_buckets=[1, 2], deadline_ms=2.0,
        dtype="float32", parallelism="single", request_timeout_ms=120_000.0,
        image_size=32, options=dict(TINY),
    )
    base.update(over)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def sd_model():
    m = build(sd_cfg())
    return m, m.init_params(jax.random.key(0)), jax.jit(m.forward)


def test_unet_flash_self_attention_matches_dense():
    """options.unet_attention='flash' routes spatial self-attention >= 1024
    tokens through the Pallas kernel with the head dim zero-padded to lane
    alignment; the padding is mathematically exact, so one UNet step must
    match the dense path to accumulation tolerance, with an identical param
    tree (the torch import mappers must keep working)."""
    import jax.numpy as jnp

    # latent 32x32 -> 1024 tokens at attention level 0: the flash path.
    cfg_d = sd_cfg(image_size=64)
    cfg_f = sd_cfg(image_size=64,
                   options={**TINY, "unet_attention": "flash"})
    md, mf = build(cfg_d), build(cfg_f)
    params = md.init_params(jax.random.key(0))
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(
        mf.init_params(jax.random.key(0)))
    lat = jax.random.normal(jax.random.key(1), (2, 32, 32, 4), jnp.float32)
    t = jnp.array([500, 500], jnp.int32)
    ctx = jax.random.normal(jax.random.key(2), (2, MAX_TOKENS, 32), jnp.float32)
    eps_d = md.unet.apply(params["unet"], lat, t, ctx)
    eps_f = mf.unet.apply(params["unet"], lat, t, ctx)
    np.testing.assert_allclose(np.asarray(eps_d), np.asarray(eps_f),
                               rtol=2e-4, atol=2e-4)


def test_unet_attention_option_validated():
    with pytest.raises(ValueError, match="unet_attention"):
        build(sd_cfg(options={**TINY, "unet_attention": "magic"}))


def test_ddim_schedule_math():
    ts, a_t, a_prev = ddim_schedule(10)
    assert ts.shape == a_t.shape == a_prev.shape == (10,)
    assert ts[0] == 999 and ts[-1] == 0
    assert (np.diff(ts) < 0).all()            # high noise -> low noise
    assert a_prev[-1] == 1.0                  # final step lands on x0
    assert (a_prev[:-1] > a_t[:-1]).all()     # denoising increases alpha
    assert (np.diff(a_t) > 0).all()


def test_txt2img_roundtrip_png(sd_model):
    from PIL import Image

    m, params, fwd = sd_model
    item = m.host_decode(b'{"prompt": "a red square", "seed": 7}',
                         "application/json")
    out = jax.tree_util.tree_map(np.asarray, fwd(params, m.assemble([item], (1,))))
    assert out["image"].shape == (1, 32, 32, 3)     # PNG edge == image_size
    png = m.host_postprocess(out, 1)[0]
    assert png[:4] == b"\x89PNG"
    assert Image.open(io.BytesIO(png)).size == (32, 32)


def test_same_prompt_seed_is_deterministic_different_seed_is_not(sd_model):
    m, params, fwd = sd_model
    a = m.host_decode(b'{"prompt": "x", "seed": 1}', "application/json")
    b = m.host_decode(b'{"prompt": "x", "seed": 2}', "application/json")
    o1 = np.asarray(fwd(params, m.assemble([a], (1,)))["image"])
    o2 = np.asarray(fwd(params, m.assemble([a], (1,)))["image"])
    o3 = np.asarray(fwd(params, m.assemble([b], (1,)))["image"])
    np.testing.assert_array_equal(o1, o2)
    assert (o1 != o3).any()


def test_padded_lanes_do_not_affect_real_lanes(sd_model):
    m, params, fwd = sd_model
    a = m.host_decode(b'{"prompt": "hello world", "seed": 3}', "application/json")
    b = m.host_decode(b'{"prompt": "other", "seed": 9}', "application/json")
    lane0_padded = np.asarray(fwd(params, m.assemble([a], (2,)))["image"])[0]
    lane0_full = np.asarray(fwd(params, m.assemble([a, b], (2,)))["image"])[0]
    np.testing.assert_array_equal(lane0_padded, lane0_full)


def test_tokenize_fixed_77(sd_model):
    m, _, _ = sd_model
    ids, neg, seed = m.host_decode(b'{"prompt": "a b c", "seed": 5}', "application/json")
    assert ids.shape == (MAX_TOKENS,) and ids.dtype == np.int32
    assert neg.shape == (MAX_TOKENS,)  # empty negative, still fixed-shape
    assert int(seed) == 5
    long = b'{"prompt": "' + b"word " * 200 + b'"}'
    ids2, _, _ = m.host_decode(long, "application/json")
    assert ids2.shape == (MAX_TOKENS,)
    with pytest.raises(ValueError):
        m.host_decode(b'{"seed": 1}', "application/json")


def test_full_size_matches_published_figures():
    """SD 1.5 published sizes: UNet 859.5M, CLIP text 123.1M, VAE decoder
    ~49.5M. Shape-only trace (eval_shape), no allocation — but the UNet
    trace alone is ~2 minutes of Python, the slowest test in the suite."""
    m = build(ModelConfig(name="sd", family="sd15", dtype="bfloat16",
                          image_size=512, options=dict(vocab_size=49408)))
    p = jax.eval_shape(m.init_params, jax.random.key(0))
    cnt = lambda t: sum(int(np.prod(x.shape))  # noqa: E731
                        for x in jax.tree_util.tree_leaves(t))
    assert 855e6 < cnt(p["unet"]) < 865e6, cnt(p["unet"])
    assert 120e6 < cnt(p["text"]) < 126e6, cnt(p["text"])
    assert 45e6 < cnt(p["vae"]) < 55e6, cnt(p["vae"])
    assert m.latent == 64


def test_orbax_roundtrip_preserves_images(sd_model, tmp_path):
    """SD params survive an orbax save/load (the production startup path)
    and regenerate the identical image."""
    from tpuserve import savedmodel

    m, params, fwd = sd_model
    path = str(tmp_path / "ckpt")
    savedmodel.save_orbax(path, params)
    m2 = build(sd_cfg(weights=path))
    restored = m2.load_params()
    item = m.host_decode(b'{"prompt": "same", "seed": 11}', "application/json")
    a = np.asarray(fwd(params, m.assemble([item], (1,)))["image"])
    b = np.asarray(jax.jit(m2.forward)(restored, m2.assemble([item], (1,)))["image"])
    np.testing.assert_array_equal(a, b)


def test_http_generate_end_to_end():
    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(models=[sd_cfg()], decode_threads=2, startup_canary=False)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()
    try:
        async def run():
            client = TestClient(TestServer(app))
            await client.start_server()
            r = await client.post(
                "/v1/models/sd:generate",
                data=b'{"prompt": "a tpu rendering images", "seed": 42}',
                headers={"Content-Type": "application/json"})
            body = await r.read()
            ctype = r.content_type
            bad = await client.post(
                "/v1/models/sd:generate", data=b'{"seed": 1}',
                headers={"Content-Type": "application/json"})
            await client.close()
            return r.status, ctype, body, bad.status

        status, ctype, body, bad_status = loop.run_until_complete(run())
        assert status == 200
        assert ctype == "image/png"
        assert body[:4] == b"\x89PNG"
        assert bad_status == 400
    finally:
        loop.close()


def test_negative_prompt_steers_and_defaults_to_empty(sd_model):
    """negative_prompt rides the CFG uncond lane: setting one changes the
    image; leaving it unset equals an explicit empty negative."""
    m, params, fwd = sd_model
    base = m.host_decode(b'{"prompt": "a cat", "seed": 4}', "application/json")
    explicit_empty = m.host_decode(
        b'{"prompt": "a cat", "negative_prompt": "", "seed": 4}',
        "application/json")
    steered = m.host_decode(
        b'{"prompt": "a cat", "negative_prompt": "a dog", "seed": 4}',
        "application/json")
    o_base = np.asarray(fwd(params, m.assemble([base], (1,)))["image"])
    o_empty = np.asarray(fwd(params, m.assemble([explicit_empty], (1,)))["image"])
    o_steer = np.asarray(fwd(params, m.assemble([steered], (1,)))["image"])
    np.testing.assert_array_equal(o_base, o_empty)
    assert not np.array_equal(o_base, o_steer)

    with pytest.raises(ValueError, match="negative_prompt"):
        m.host_decode(b'{"prompt": "x", "negative_prompt": 5}',
                      "application/json")


def _write_tiny_bpe(tmp_path):
    import json as _json

    vocab = {"<|startoftext|>": 0, "<|endoftext|>": 1, "a</w>": 2,
             "cat</w>": 3, "c": 4, "at</w>": 5, "a": 6, "t</w>": 7}
    (tmp_path / "vocab.json").write_text(_json.dumps(vocab))
    (tmp_path / "merges.txt").write_text("#version: 0.2\na t</w>\nc at</w>\n")
    return str(tmp_path / "vocab.json"), str(tmp_path / "merges.txt")


def test_clip_bpe_tokenizer_contract(tmp_path):
    """CLIP-style byte-level BPE behind the WordPiece encode() contract:
    BOS + merged pieces + EOS, EOS-padded fixed length."""
    from tpuserve.text import CLIPBPETokenizer

    vocab_file, merges_file = _write_tiny_bpe(tmp_path)
    tok = CLIPBPETokenizer(vocab_file, merges_file)
    ids, mask = tok.encode("a cat", 8)
    assert ids.shape == (8,) and mask.shape == (8,)
    assert list(ids[:4]) == [0, 2, 3, 1]  # BOS, a</w>, merged cat</w>, EOS
    assert list(mask) == [1, 1, 1, 1, 0, 0, 0, 0]
    assert ids[4:].tolist() == [tok.pad_id] * 4  # EOS-padded


def test_sd15_serves_with_bpe_tokenizer(tmp_path):
    """options.bpe_vocab/bpe_merges swap the prompt tokenizer by config."""
    vocab_file, merges_file = _write_tiny_bpe(tmp_path)
    m = build(sd_cfg(options={**TINY, "bpe_vocab": vocab_file,
                              "bpe_merges": merges_file}))
    ids, neg, seed = m.host_decode(b'{"prompt": "a cat", "seed": 2}',
                                   "application/json")
    assert ids.shape == (MAX_TOKENS,) and list(ids[:4]) == [0, 2, 3, 1]
    assert m.text_encoder.vocab_size == 8  # sized from the BPE vocab
