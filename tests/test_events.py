"""Structured event plane, black box, and audit trail (ISSUE 15).

Layers, the test_trace discipline:

- pure units: event-ring bounds + newest-kept ordering, query filtering,
  the stdlib-logging bridge, audit/postmortem ledgers (counters, FIFO
  bounds, signal naming), the black-box writer's atomic checkpoints, and
  the /debug/events query validator;
- HTTP e2e on a real single-process server: /debug/events carries bridged
  log lines and trace-correlated request events, junk query params 400
  (the /debug/trace hardening), a rejected reload leaves an audit record
  naming the failing gate, and /debug/trace?trace_id= interleaves the
  matching events into both the record and the Chrome output;
- a REAL 2-worker router fleet: SIGKILL one worker and the supervisor's
  postmortem names the signal, carries the dead worker's stderr tail
  (boot banner included) and its black-box snapshot, and the fleet
  :reload lands in /debug/audit with per-worker outcomes.

No pytest-asyncio in the image: module-level event loops drive everything
explicitly (the test_router idiom).
"""

import asyncio
import io
import json
import logging
import os
import signal
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.config import (EventsConfig, FaultRuleConfig, FaultsConfig,
                             ModelConfig, RouterConfig, ServerConfig,
                             TraceConfig)
from tpuserve.obs import Metrics
from tpuserve.telemetry.events import (AuditLog, BlackBoxWriter, EventLog,
                                       EventLogBridge, PostmortemLog,
                                       events_to_chrome, install_bridge,
                                       parse_events_query, read_snapshot,
                                       read_tail, signal_name)

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning")

NPY = "application/x-npy"


def npy_bytes(seed: int = 0) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (8, 8, 3), dtype=np.uint8))
    return buf.getvalue()


def _toy(name: str = "toy", **kw) -> ModelConfig:
    base = dict(family="toy", batch_buckets=[1, 2], deadline_ms=2.0,
                dtype="float32", num_classes=10, parallelism="single",
                request_timeout_ms=10_000.0, wire_size=8)
    base.update(kw)
    return ModelConfig(name=name, **base)


# ---------------------------------------------------------------------------
# Pure units
# ---------------------------------------------------------------------------

def test_ring_bounds_and_newest_kept_ordering():
    el = EventLog(Metrics(16), capacity=8)
    for i in range(20):
        el.emit("info", "test", f"e{i}", seq=i)
    evs = el.query()
    # bounded at capacity, oldest dropped, order preserved oldest-first
    assert len(evs) == 8
    assert [e["fields"]["seq"] for e in evs] == list(range(12, 20))
    # limit keeps the NEWEST matches; limit=0 is empty, not everything
    assert [e["fields"]["seq"] for e in el.query(limit=3)] == [17, 18, 19]
    assert el.query(limit=0) == []
    # monotone timestamps
    ts = [e["ts_us"] for e in evs]
    assert ts == sorted(ts)


def test_query_filters_compose():
    el = EventLog(Metrics(16), capacity=64)
    el.emit("info", "http", "a", trace_id="aa" * 16)
    el.emit("warning", "http", "b", trace_id="bb" * 16)
    el.emit("warning", "lifecycle", "c")
    mid = el.query()[-1]["ts_us"]
    el.emit("error", "http", "d", trace_id="bb" * 16)
    assert [e["event"] for e in el.query(level="warning")] == ["b", "c"]
    assert [e["event"] for e in el.query(subsystem="http")] == ["a", "b", "d"]
    assert [e["event"] for e in el.query(trace_id="bb" * 16)] == ["b", "d"]
    assert [e["event"] for e in el.query(since_us=mid)] == ["c", "d"]
    assert [e["event"] for e in el.query(subsystem="http",
                                         trace_id="bb" * 16,
                                         level="error")] == ["d"]


def test_events_logged_counters_split_by_level_and_subsystem():
    m = Metrics(16)
    el = EventLog(m, capacity=16)
    el.emit("info", "http", "x")
    el.emit("info", "http", "y")
    el.emit("error", "batcher", "z")
    cv = m.counter_values()
    assert cv["events_logged_total{level=info,subsystem=http}"] == 2.0
    assert cv["events_logged_total{level=error,subsystem=batcher}"] == 1.0


def test_logging_bridge_captures_existing_tpuserve_logger():
    """The point of the bridge: an EXISTING `log = logging.getLogger(
    "tpuserve.lifecycle")` call site flows into the ring with no rewrite —
    subsystem from the logger suffix, level mapped, message rendered."""
    el = EventLog(Metrics(16), capacity=16)
    install_bridge(el, "INFO")
    try:
        logging.getLogger("tpuserve.lifecycle").warning(
            "reload rejected at %s gate", "integrity")
        logging.getLogger("tpuserve.workerproc").info("worker %d up", 3)
        logging.getLogger("tpuserve.lifecycle").debug("below bridge_level")
        evs = el.query()
        assert [(e["subsystem"], e["level"]) for e in evs] == [
            ("lifecycle", "warning"), ("workerproc", "info")]
        assert evs[0]["msg"] == "reload rejected at integrity gate"
    finally:
        logging.getLogger("tpuserve").handlers.clear()


def test_bridge_never_raises():
    class Boom:
        def emit(self, *a, **k):
            raise RuntimeError("ring on fire")

    h = EventLogBridge(Boom())
    rec = logging.LogRecord("tpuserve.x", logging.INFO, __file__, 1,
                            "msg", None, None)
    h.emit(rec)  # swallowed: a logging handler must never take logging down


def test_parse_events_query_hardening():
    ok = parse_events_query({"since_us": "12.5", "level": "warning",
                             "subsystem": "http", "trace_id": "ab",
                             "limit": "7"})
    assert ok == {"since_us": 12.5, "level": "warning", "subsystem": "http",
                  "trace_id": "ab", "limit": 7}
    assert parse_events_query({}) == {"limit": 1000}
    for junk in ({"level": "loud"}, {"since_us": "yesterday"},
                 {"limit": "many"}, {"limit": "-1"}, {"bogus": "1"}):
        with pytest.raises(ValueError):
            parse_events_query(junk)


def test_audit_log_fifo_and_counters():
    m = Metrics(16)
    au = AuditLog(m, capacity=2)
    au.record("reload", "toy", "ok", duration_ms=10.0, version=2)
    au.record("reload", "toy", "rejected", stage="integrity")
    au.record("drain", "server", "ok")
    dump = au.dump()  # newest first, bounded
    assert [r["verb"] for r in dump] == ["drain", "reload"]
    assert dump[1]["stage"] == "integrity"
    cv = m.counter_values()
    assert cv["audit_events_total{verb=reload,outcome=ok}"] == 1.0
    assert cv["audit_events_total{verb=reload,outcome=rejected}"] == 1.0
    assert cv["audit_events_total{verb=drain,outcome=ok}"] == 1.0


def test_postmortem_capture_reads_tail_and_snapshot(tmp_path):
    m = Metrics(16)
    el = EventLog(m, capacity=16)
    pm = PostmortemLog(m, capacity=4, tail_bytes=32, events=el)
    stderr = tmp_path / "w0.stderr"
    stderr.write_text("x" * 100 + "final words")
    snap = tmp_path / "w0.snapshot.json"
    snap.write_text(json.dumps({"events": [{"event": "e"}], "pid": 7}))
    rec = pm.capture_blocking("worker", "worker0", 1234, -signal.SIGKILL,
                              stderr_path=str(stderr),
                              snapshot_path=str(snap), worker=0)
    assert rec["signal"] == "SIGKILL" and rec["exitcode"] == -9
    assert rec["stderr_tail"].endswith("final words")
    assert len(rec["stderr_tail"]) <= 32  # tail, not the whole file
    assert rec["snapshot"]["pid"] == 7
    assert m.counter_values()[
        "postmortems_total{component=worker,signal=SIGKILL}"] == 1.0
    # mirrored into the event ring for the flight data
    assert any(e["event"] == "postmortem" for e in el.query())
    # missing files degrade to None fields, never raise
    rec2 = pm.capture_blocking("worker", "worker1", 1, 0,
                               stderr_path=str(tmp_path / "nope"),
                               snapshot_path=str(tmp_path / "nope2"))
    assert rec2["signal"] is None and rec2["stderr_tail"] is None \
        and rec2["snapshot"] is None
    assert signal_name(-signal.SIGTERM) == "SIGTERM"
    assert read_tail(None, 10) is None and read_snapshot(None) is None


def test_blackbox_writer_atomic_and_initial_snapshot(tmp_path):
    path = str(tmp_path / "sub" / "snap.json")
    calls = []

    def collect():
        calls.append(1)
        return {"n": len(calls)}

    bb = BlackBoxWriter(path, interval_s=30.0, collect=collect)
    bb.start()
    try:
        deadline = time.monotonic() + 5.0
        while not os.path.exists(path) and time.monotonic() < deadline:
            time.sleep(0.01)
        # one snapshot immediately at start (a kill right after boot still
        # has evidence), atomic (no .tmp left behind)
        assert json.load(open(path)) == {"n": 1}
        assert not os.path.exists(path + ".tmp")
    finally:
        bb.stop()
    assert not bb.is_alive()
    # a collect() that raises skips the tick rather than killing the thread
    bad = BlackBoxWriter(str(tmp_path / "bad.json"), 30.0,
                         lambda: (_ for _ in ()).throw(RuntimeError()))
    bad.write_once()
    assert not os.path.exists(str(tmp_path / "bad.json"))


def test_events_to_chrome_instant_events():
    el = EventLog(Metrics(16), capacity=8, pid=3)
    el.emit("warning", "http", "request_error", model="toy",
            trace_id="cd" * 16, status=500)
    (ev,) = events_to_chrome(el.query())
    assert ev["ph"] == "i" and ev["pid"] == 3
    assert ev["name"] == "http:request_error"
    assert ev["args"]["trace_id"] == "cd" * 16
    assert ev["args"]["status"] == 500


def test_events_config_validation():
    with pytest.raises(ValueError, match="capacity"):
        EventsConfig(capacity=0)
    with pytest.raises(ValueError, match="bridge_level"):
        EventsConfig(bridge_level="LOUD")
    with pytest.raises(ValueError, match="snapshot_interval_s"):
        EventsConfig(snapshot_interval_s=-1.0)
    EventsConfig(bridge_level="warning")  # case-insensitive ok


# ---------------------------------------------------------------------------
# Over HTTP: single-process server
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def client(loop, tmp_path_factory):
    from tpuserve.server import ServerState, make_app

    snap = str(tmp_path_factory.mktemp("events") / "snap.json")
    cfg = ServerConfig(
        models=[_toy()],
        decode_threads=2,
        trace=TraceConfig(slow_n=8, error_capacity=32),
        events=EventsConfig(snapshot_interval_s=0.2, snapshot_path=snap),
        faults=FaultsConfig(enabled=True, rules=[
            FaultRuleConfig(kind="reload_corrupt", model="toy",
                            probability=1.0),
        ]),
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def setup():
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    c = loop.run_until_complete(setup())
    yield lambda coro: loop.run_until_complete(coro), c, state, snap
    loop.run_until_complete(c.close())


def test_debug_events_carries_bridged_and_request_events(client):
    run, c, state, _ = client

    async def go():
        # A 400 (garbage body) leaves a trace-correlated request_error.
        resp = await c.post("/v1/models/toy:predict", data=b"junk",
                            headers={"Content-Type": NPY})
        assert resp.status == 400
        tid = resp.headers["X-Trace-Id"]
        r = await c.get("/debug/events")
        assert r.status == 200
        body = await r.json()
        assert body["size"] > 0 and body["capacity"] == 4096
        evs = body["events"]
        # bridged startup log lines flowed in (server subsystem at least)
        assert any(e["event"] == "log" for e in evs)
        mine = [e for e in evs if e.get("trace_id") == tid]
        assert mine and mine[0]["event"] == "request_error"
        assert mine[0]["fields"]["status"] == 400
        # filter down over HTTP
        r = await c.get(f"/debug/events?trace_id={tid}&subsystem=http")
        filt = (await r.json())["events"]
        assert len(filt) == 1 and filt[0]["trace_id"] == tid

    run(go())


def test_debug_events_junk_params_400(client):
    run, c, state, _ = client

    async def go():
        for q in ("level=loud", "since_us=yesterday", "limit=many",
                  "limit=-2", "bogus=1"):
            r = await c.get(f"/debug/events?{q}")
            assert r.status == 400, q
            assert "error" in await r.json()

    run(go())


def test_rejected_reload_leaves_audit_record(client):
    run, c, state, _ = client

    async def go():
        r = await c.post("/admin/models/toy:reload")
        assert r.status == 409  # reload_corrupt @ 100% -> integrity gate
        r = await c.get("/debug/audit")
        assert r.status == 200
        audit = (await r.json())["audit"]
        rec = next(a for a in audit if a["verb"] == "reload")
        assert rec["target"] == "toy" and rec["outcome"] == "rejected"
        assert rec["stage"] == "integrity"
        assert rec["duration_ms"] >= 0
        # the lifecycle's structured rejection event landed too
        r = await c.get("/debug/events?subsystem=lifecycle")
        evs = (await r.json())["events"]
        assert any(e["event"] == "reload_rejected"
                   and e["fields"]["stage"] == "integrity" for e in evs)

    run(go())


def test_trace_event_interleave_by_trace_id(client):
    run, c, state, _ = client

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=b"junk",
                            headers={"Content-Type": NPY})
        tid = resp.headers["X-Trace-Id"]
        # record format: spans AND correlated events on one record
        r = await c.get(f"/debug/trace?trace_id={tid}&format=record")
        assert r.status == 200
        rec = await r.json()
        assert rec["spans"] and rec["events"]
        assert all(e["trace_id"] == tid for e in rec["events"])
        # chrome format: the events ride as instant marks beside the spans
        r = await c.get(f"/debug/trace?trace_id={tid}")
        trace = json.loads(await r.text())
        phases = {e["ph"] for e in trace["traceEvents"]}
        assert phases == {"X", "i"}
        inst = [e for e in trace["traceEvents"] if e["ph"] == "i"]
        assert any(e["args"].get("trace_id") == tid for e in inst)

    run(go())


def test_blackbox_snapshot_checkpoints(client):
    run, c, state, snap = client

    async def go():
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            data = read_snapshot(snap)
            if data and data.get("counters"):
                break
            await asyncio.sleep(0.05)
        data = read_snapshot(snap)
        assert data is not None, "black box never checkpointed"
        assert data["pid"] == os.getpid()
        assert isinstance(data["events"], list) and data["events"]
        assert any(k.startswith("requests_total")
                   for k in data["counters"])
        assert "flight" in data

    run(go())


def test_stats_events_block_and_disabled_409(client, loop):
    run, c, state, _ = client

    async def go():
        r = await c.get("/stats")
        block = (await r.json())["events"]
        assert block["size"] > 0
        assert "audit" in block and "postmortems" in block

    run(go())

    # disabled plane: endpoints answer 409, nothing is constructed
    from tpuserve.server import ServerState

    cfg2 = ServerConfig(models=[_toy("t2")],
                        events=EventsConfig(enabled=False))
    s2 = ServerState(cfg2)
    assert s2.events is None and s2.audit is None and s2.postmortems is None


# ---------------------------------------------------------------------------
# The black box end-to-end: a REAL 2-worker fleet, SIGKILL one worker
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet(loop, tmp_path_factory):
    from aiohttp import web

    from tpuserve.workerproc.router import RouterState, make_router_app

    bb_dir = str(tmp_path_factory.mktemp("blackbox"))
    cfg = ServerConfig(
        decode_threads=2, startup_canary=False, drain_timeout_s=3.0,
        watchdog_interval_s=0.2,
        router=RouterConfig(enabled=True, workers=2, retry_max=2,
                            health_interval_s=0.2, unhealthy_after=2,
                            respawn_initial_s=0.3, respawn_max_s=2.0),
        events=EventsConfig(dir=bb_dir, snapshot_interval_s=0.2),
        models=[_toy()],
    )
    state = RouterState(cfg)
    runner = web.AppRunner(make_router_app(state), access_log=None)

    async def setup():
        await runner.setup()  # on_startup -> supervisor spawns the fleet
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return runner.addresses[0][1]

    port = loop.run_until_complete(setup())
    yield (lambda coro: loop.run_until_complete(coro), state, port)
    loop.run_until_complete(runner.cleanup())


async def _fleet_get(port: int, path: str):
    import aiohttp

    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{port}{path}",
                         timeout=aiohttp.ClientTimeout(total=15.0)) as r:
            return r.status, await r.json()


def test_worker_sigkill_leaves_full_postmortem(fleet):
    """The tentpole black-box contract: SIGKILL a worker mid-life and the
    reaped slot's postmortem names SIGKILL, carries the dead process's
    stderr tail (boot banner at minimum — logging writes to stderr), and
    its last black-box snapshot with events recorded BEFORE death."""
    run, state, port = fleet

    async def go():
        import aiohttp

        # serve one request so the worker has flight data to checkpoint
        async with aiohttp.ClientSession() as s:
            async with s.post(
                    f"http://127.0.0.1:{port}/v1/models/toy:predict",
                    data=npy_bytes(), headers={"Content-Type": NPY}) as r:
                assert r.status == 200
        # give the 0.2s black box a couple of ticks
        await asyncio.sleep(0.6)
        victim = state.supervisor.pick()
        assert victim is not None
        os.kill(victim.pid, signal.SIGKILL)
        deadline = time.monotonic() + 20.0
        records = []
        while time.monotonic() < deadline:
            _, body = await _fleet_get(port, "/debug/postmortems")
            records = body["postmortems"]
            if any(r["signal"] == "SIGKILL" for r in records):
                break
            await asyncio.sleep(0.1)
        rec = next(r for r in records if r["signal"] == "SIGKILL")
        assert rec["component"] == "worker"
        assert rec["pid"] == victim.pid and rec["exitcode"] == -9
        assert rec["stderr_tail"], "stderr capture lost"
        snap = rec["snapshot"]
        assert snap is not None, "black-box snapshot lost"
        assert snap["worker_id"] == victim.wid
        assert isinstance(snap["events"], list) and snap["events"]
        # the death is flight data on the router too
        _, body = await _fleet_get(port, "/debug/events?subsystem="
                                         "supervision")
        assert any(e["event"] == "postmortem" for e in body["events"])
        # metric: postmortems_total{component=worker,signal=SIGKILL}
        assert state.metrics.counter_values()[
            "postmortems_total{component=worker,signal=SIGKILL}"] >= 1.0
        # wait for the respawn so the next test sees a whole fleet
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if len(state.supervisor.healthy_workers()) == 2:
                return
            await asyncio.sleep(0.1)
        raise AssertionError("victim never respawned")

    run(go())


def test_fleet_reload_lands_in_audit_with_per_worker_outcomes(fleet):
    run, state, port = fleet

    async def go():
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{port}"
                              "/admin/models/toy:reload") as r:
                assert r.status == 200, await r.text()
        _, body = await _fleet_get(port, "/debug/audit")
        rec = next(a for a in body["audit"] if a["verb"] == "reload")
        assert rec["outcome"] == "ok" and rec["target"] == "toy"
        assert rec["generation"] == state.generations["toy"]
        assert set(rec["per_worker"]) == {"0", "1"}
        assert all(v == 200 for v in rec["per_worker"].values())

    run(go())


def test_worker_events_proxy(fleet):
    run, state, port = fleet

    async def go():
        status, body = await _fleet_get(port, "/workers/0/debug/events")
        assert status == 200 and body["events"]
        # worker events ride the worker's process lane (wid + 1)
        assert all(e["pid"] == 1 for e in body["events"])
        # junk params 400 straight through the proxy
        status, _ = await _fleet_get(port,
                                     "/workers/0/debug/events?level=loud")
        assert status == 400

    run(go())
