"""Metrics/histograms/tracer (C8)."""

import json
import logging
import sys

from tpuserve.obs import Histogram, Metrics, percentile


def test_histogram_quantiles():
    h = Histogram("lat")
    for v in [1.0] * 90 + [100.0] * 10:
        h.observe(v)
    assert h.n == 100
    assert h.quantile(0.5) <= 2.0
    assert h.quantile(0.99) >= 50.0


def test_histogram_quantile_interpolated():
    """Bucket-boundary artifacts (VERDICT r3 weak 4): a 940 ms-mean sample
    must report a ~940 ms p50, not the next power-of-two bound, and tail
    quantiles must land near the sample max, not a 100 s bucket edge."""
    h = Histogram("lat")
    for v in [900.0, 920.0, 940.0, 960.0, 980.0] * 20:
        h.observe(v)
    assert 850 <= h.quantile(0.5) <= 1000
    assert 900 <= h.quantile(0.99) <= 1100
    # Worst case relative error of the log-linear buckets is bounded
    h2 = Histogram("lat2")
    for _ in range(1000):
        h2.observe(23.0)
    assert 20 <= h2.quantile(0.5) <= 30
    assert 20 <= h2.quantile(0.99) <= 30


def test_metrics_prometheus_render():
    m = Metrics()
    m.counter("requests_total{model=rn}").inc(3)
    m.gauge("queue_depth{model=rn}").set(7)
    m.observe_phase("rn", "total", 12.5)
    text = m.render_prometheus()
    assert 'requests_total{model="rn"} 3' in text  # label values quoted
    assert 'queue_depth{model="rn"} 7' in text
    assert "# TYPE latency_ms histogram" in text
    assert 'model="rn"' in text and 'phase="total"' in text
    # one TYPE line per metric base name even with multiple label sets
    m.counter("requests_total{model=other}").inc()
    text = m.render_prometheus()
    assert text.count("# TYPE requests_total counter") == 1


def test_metrics_summary():
    m = Metrics()
    m.observe_phase("rn", "total", 10.0)
    m.observe_phase("rn", "total", 20.0)
    s = m.summary()
    key = "latency_ms{model=rn,phase=total}"
    assert s["latency"][key]["n"] == 2
    assert 10 <= s["latency"][key]["mean_ms"] <= 20


def test_tracer_chrome_format():
    m = Metrics()
    m.tracer.add("compute", 100.0, 100.010, tid="rn", batch=8)
    data = json.loads(m.tracer.chrome_trace())
    (ev,) = data["traceEvents"]
    assert ev["name"] == "compute"
    assert ev["ph"] == "X"
    assert abs(ev["dur"] - 10_000) < 1
    assert ev["args"]["batch"] == 8


def test_observe_bisect_matches_linear_scan():
    """ISSUE 12 satellite: bucket assignment via bisect_left must be
    bit-identical to the old linear scan (first bound with value <= b,
    overflow past the last) for every boundary case."""
    h = Histogram("lat")
    bounds = h.bounds

    def linear_bucket(value):
        for i, b in enumerate(bounds):
            if value <= b:
                return i
        return len(bounds)

    probes = [0.0, -1.0, -0.001, 0.05, 0.1, 0.100001, 1e5, 1e5 + 1, 1e9,
              float("inf")]
    probes += list(bounds)                      # exact bounds land IN bucket
    probes += [b * 1.0000001 for b in bounds]   # just past -> next bucket
    probes += [b * 0.9999999 for b in bounds]
    for v in probes:
        h2 = Histogram("probe")
        h2.observe(v)
        assert h2.counts[linear_bucket(v)] == 1, \
            f"value {v}: bisect bucket != linear bucket {linear_bucket(v)}"


def test_histogram_exemplars_rendered():
    """[trace] exemplars: the last trace id observed in a bucket renders in
    OpenMetrics exemplar syntax on that bucket's /metrics line."""
    m = Metrics()
    tid = "ab" * 16
    m.histogram("latency_ms{model=t,phase=total}").observe(12.0, trace_id=tid)
    m.histogram("latency_ms{model=t,phase=total}").observe(13.0)  # untraced
    text = m.render_prometheus()
    ex_lines = [ln for ln in text.splitlines() if "# {trace_id=" in ln]
    assert len(ex_lines) == 1
    assert f'# {{trace_id="{tid}"}} 12 ' in ex_lines[0]
    assert ex_lines[0].startswith("latency_ms_bucket{")
    # A later traced observation in the same bucket overwrites the slot.
    m.histogram("latency_ms{model=t,phase=total}").observe(12.5,
                                                           trace_id="cd" * 16)
    assert 'trace_id="cdcdcdcdcdcdcdcdcdcdcdcdcdcdcdcd"' \
        in m.render_prometheus()


def test_histogram_exemplars_disabled():
    m = Metrics(exemplars=False)
    m.histogram("latency_ms{model=t,phase=total}").observe(12.0,
                                                           trace_id="ab" * 16)
    assert "# {trace_id=" not in m.render_prometheus()


def test_percentile_exact():
    assert percentile([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], 0.5) == 5
    assert percentile([], 0.5) == 0.0
    assert percentile([42], 0.99) == 42


def test_prometheus_label_values_escaped():
    """Quotes/backslashes/newlines in label values must not corrupt the
    exposition format (ADVICE r1, unfixed through r2)."""
    m = Metrics()
    m.counter('requests_total{model=we"ird\\name}').inc()
    text = m.render_prometheus()
    assert 'model="we\\"ird\\\\name"' in text
    # Still exactly one sample line for the counter
    assert sum(1 for line in text.splitlines()
               if line.startswith("requests_total{")) == 1


def test_json_log_formatter_emits_parseable_lines():
    from tpuserve.server import JsonLogFormatter

    fmt = JsonLogFormatter()
    rec = logging.LogRecord("tpuserve.x", logging.INFO, __file__, 1,
                            "served %d items", (42,), None)
    out = json.loads(fmt.format(rec))
    assert out["msg"] == "served 42 items"
    assert out["level"] == "INFO" and out["logger"] == "tpuserve.x"

    try:
        raise RuntimeError("boom")
    except RuntimeError:
        rec2 = logging.LogRecord("tpuserve.x", logging.ERROR, __file__, 1,
                                 "failed", (), sys.exc_info())
    out2 = json.loads(fmt.format(rec2))
    assert "boom" in out2["exc"]
