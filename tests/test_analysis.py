"""Static-analysis suite (ISSUE 4): every rule family against the fixture
snippets under tests/fixtures/analysis/ (positive AND negative cases), the
drift rules against a synthetic mini-repo, the baseline workflow, and the
real tree staying clean vs the checked-in baseline."""

import json
from pathlib import Path

from tpuserve.analysis import astlint, drift
from tpuserve.analysis.findings import Finding, compare, load_baseline, save_baseline

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"
ROOT = Path(__file__).resolve().parents[1]


def run_fixture(name):
    return astlint.run_paths([FIXTURES / name], FIXTURES)


# ---------------------------------------------------------------------------
# TPS101 / TPS102: blocking on the event loop
# ---------------------------------------------------------------------------

def test_blocking_in_async_positive_cases():
    found = {(f.rule, f.symbol) for f in run_fixture("async_blocking.py")}
    assert ("TPS101", "Handler.bad_sleep") in found
    assert ("TPS101", "Handler.bad_result") in found
    assert ("TPS101", "Handler.bad_acquire") in found
    assert ("TPS102", "Handler.bad_held_across_await") in found


def test_blocking_reachable_through_sync_helper():
    hits = [f for f in run_fixture("async_blocking.py")
            if f.symbol == "Handler.bad_reachable"]
    assert hits, "blocking helper called from async body not flagged"
    assert "_helper" in hits[0].message  # the path is named


def test_blocking_negative_cases():
    bad = [f for f in run_fixture("async_blocking.py") if "good_" in f.symbol]
    assert not bad, [f.render() for f in bad]


# ---------------------------------------------------------------------------
# TPS201: lock-order cycles
# ---------------------------------------------------------------------------

def test_lock_order_inversion_detected():
    cycles = [f for f in run_fixture("lock_order.py") if f.rule == "TPS201"]
    nested = [f for f in cycles if "Inverted._a" in f.symbol]
    assert nested, [f.render() for f in cycles]
    # Both directions' acquisition sites are named in the message.
    assert "one" in nested[0].message and "two" in nested[0].message


def test_lock_order_call_edge_detected():
    cycles = [f for f in run_fixture("lock_order.py")
              if f.rule == "TPS201" and "CrossCall" in f.symbol]
    assert cycles, "m->n edge created through a call while m held was missed"


def test_lock_order_consistent_ordering_clean():
    assert not [f for f in run_fixture("lock_order.py") if "Ordered" in f.symbol]


# ---------------------------------------------------------------------------
# TPS301: unguarded cross-context writes
# ---------------------------------------------------------------------------

def test_shared_state_race_detected():
    found = {f.symbol for f in run_fixture("shared_state.py")
             if f.rule == "TPS301"}
    assert "Racy.items" in found and "Racy.count" in found, found


def test_shared_state_guarded_and_entry_held_clean():
    found = {f.symbol for f in run_fixture("shared_state.py")
             if f.rule == "TPS301"}
    assert not any("Guarded" in s or "EntryHeld" in s for s in found), found


# ---------------------------------------------------------------------------
# TPS4xx drift rules (synthetic mini-repo so the cases are hermetic)
# ---------------------------------------------------------------------------

def _mini_repo(tmp_path, *, document=False):
    (tmp_path / "tpuserve").mkdir()
    (tmp_path / "examples").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "tests").mkdir()
    (tmp_path / "tpuserve" / "config.py").write_text(
        "from dataclasses import dataclass\n"
        'FAULT_KINDS = ("boom",)\n'
        "@dataclass\n"
        "class ModelConfig:\n"
        "    knob_a: int = 1\n"
        "    knob_b: int = 2\n"
    )
    (tmp_path / "tpuserve" / "obs.py").write_text(
        'class M:\n    def f(self, m):\n        m.counter(f"widgets_total{x}").inc()\n'
    )
    toml = "knob_a = 1\n" + ("knob_b = 2\n" if document else "")
    (tmp_path / "examples" / "serve_all.toml").write_text(toml)
    docs = "knob_a knob_b\n" if document else "knob_a\n"
    if document:
        docs += "widgets_total\n"
    (tmp_path / "README.md").write_text(docs)
    (tmp_path / "tests" / "test_x.py").write_text(
        'KIND = "boom"\n' if document else "pass\n")
    return tmp_path


def test_drift_rules_flag_missing(tmp_path):
    found = {(f.rule, f.symbol) for f in drift.run(_mini_repo(tmp_path))}
    assert ("TPS401", "ModelConfig.knob_b") in found
    assert ("TPS402", "metric.widgets_total") in found
    assert ("TPS403", "fault.boom") in found
    assert not any(s == "ModelConfig.knob_a" for _r, s in found)


def test_drift_rules_clean_when_documented(tmp_path):
    assert drift.run(_mini_repo(tmp_path, document=True)) == []


# ---------------------------------------------------------------------------
# Baseline workflow
# ---------------------------------------------------------------------------

def test_baseline_roundtrip_and_compare(tmp_path):
    old = Finding(rule="TPS101", file="a.py", symbol="f", message="m", line=3)
    new = Finding(rule="TPS201", file="b.py", symbol="g", message="n", line=9)
    path = tmp_path / "baseline.json"
    save_baseline(path, [old])
    baseline = load_baseline(path)
    fresh, stale = compare([old, new], baseline)
    assert fresh == [new] and not stale
    # Line numbers are not identity: the same finding moved does not re-fail.
    moved = Finding(rule="TPS101", file="a.py", symbol="f", message="m", line=99)
    fresh, stale = compare([moved], baseline)
    assert not fresh and not stale
    # A fixed finding surfaces as a stale baseline entry, never silently.
    fresh, stale = compare([], baseline)
    assert not fresh and stale == {old.key}


def test_baseline_file_is_valid_json():
    data = json.loads((ROOT / "tpuserve" / "analysis" / "baseline.json").read_text())
    assert isinstance(data["findings"], list)


# ---------------------------------------------------------------------------
# The real tree: lint must run clean against the checked-in baseline (the
# same gate CI runs via `python -m tpuserve lint`).
# ---------------------------------------------------------------------------

def test_repo_lint_clean_vs_baseline():
    findings = astlint.run_paths(
        astlint.collect_files([ROOT / "tpuserve"]), ROOT)
    findings += drift.run(ROOT)
    baseline = load_baseline(ROOT / "tpuserve" / "analysis" / "baseline.json")
    new, _stale = compare(findings, baseline)
    assert not new, "new lint findings:\n" + "\n".join(f.render() for f in new)


def test_lint_cli_exit_codes(tmp_path):
    from tpuserve.cli import main

    assert main(["lint"]) == 0
    # --no-baseline over the fixtures must fail (they are all positives).
    assert main(["lint", "--no-baseline", str(FIXTURES)]) == 1
    assert main(["lint", str(tmp_path / "missing")]) == 2
