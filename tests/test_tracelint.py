"""Trace-discipline (TPS5xx) and ledger-escape (TPS6xx) analysis suite:
every rule against the fixture snippets (positive AND negative cases),
the sanction filter, the whole repo tree staying clean with an EMPTY
baseline, and the runtime retrace witness — a deliberate post-barrier
compile raises RetraceViolation naming the (tag, variant) while the
clean path stays silent with compile delta 0."""

from pathlib import Path

import pytest

from tpuserve.analysis import ledgerlint, tracelint, witness

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"
ROOT = Path(__file__).resolve().parents[1]


def trace_fixture(name):
    return tracelint.run_paths([FIXTURES / name], FIXTURES)


def ledger_fixture(name):
    return ledgerlint.run_paths([FIXTURES / name], FIXTURES)


# ---------------------------------------------------------------------------
# TPS501: per-call-fresh compile-cache entries
# ---------------------------------------------------------------------------

def test_jit_of_fresh_callable_flagged():
    found = {(f.rule, f.symbol)
             for f in trace_fixture("trace_discipline.py")}
    assert ("TPS501", "bad_jit_lambda") in found
    assert ("TPS501", "bad_jit_local_def") in found


def test_fresh_literal_in_static_position_flagged():
    hits = [f for f in trace_fixture("trace_discipline.py")
            if f.rule == "TPS501" and f.symbol == "bad_fresh_static"]
    assert hits and "static_argnames" in hits[0].message


def test_aot_lower_compile_is_exempt():
    assert not [f for f in trace_fixture("trace_discipline.py")
                if f.symbol == "good_aot_local"]


# ---------------------------------------------------------------------------
# TPS502: host-forcing ops on traced values
# ---------------------------------------------------------------------------

def test_host_forcing_ops_flagged():
    msgs = [f.message for f in trace_fixture("trace_discipline.py")
            if f.rule == "TPS502" and f.symbol == "bad_host_forcing"]
    assert any("float()" in m for m in msgs)
    assert any(".item()" in m for m in msgs), \
        "taint must flow through tracer method calls (x.mean())"
    assert any("print()" in m for m in msgs)
    assert any("np.log()" in m for m in msgs)


# ---------------------------------------------------------------------------
# TPS503: Python control flow on traced values
# ---------------------------------------------------------------------------

def test_traced_branches_flagged():
    msgs = [f.message for f in trace_fixture("trace_discipline.py")
            if f.rule == "TPS503" and f.symbol == "bad_traced_branch"]
    assert any("`if`" in m for m in msgs)
    assert any("`while`" in m for m in msgs)


def test_conventional_model_entry_point_is_traced():
    hits = [f for f in trace_fixture("trace_discipline.py")
            if f.rule == "TPS503" and f.symbol == "ToyGen.step"]
    assert hits, "GenerativeModel.step must be in the jit-reachability set"


def test_static_reads_and_kwonly_args_clean():
    bad = [f for f in trace_fixture("trace_discipline.py")
           if f.symbol in ("good_static_reads", "good_kwonly_static")]
    assert not bad, [f.render() for f in bad]


def test_sanction_annotation_filters_the_named_rule():
    assert not [f for f in trace_fixture("trace_discipline.py")
                if f.symbol == "good_sanctioned"]
    # The annotation requires a reason and an exact rule match.
    assert tracelint.sanctioned_rules(
        "x = 1  # tps-ok[TPS503]: structure check") == {"TPS503"}
    assert tracelint.sanctioned_rules(
        "x = 1  # tps-ok[TPS501,TPS505]: factory") == {"TPS501", "TPS505"}
    assert tracelint.sanctioned_rules("x = 1  # tps-ok[TPS503]:") == set()
    assert tracelint.sanctioned_rules("x = 1  # tps-ok: because") == set()


# ---------------------------------------------------------------------------
# TPS504 / TPS505: retrace-by-closure
# ---------------------------------------------------------------------------

def test_closure_capture_of_enclosing_arg_flagged():
    hits = [f for f in trace_fixture("trace_discipline.py")
            if f.rule == "TPS505" and f.symbol == "bad_capture_arg"]
    assert hits and "'n'" in hits[0].message


def test_closure_capture_of_fresh_array_flagged():
    hits = [f for f in trace_fixture("trace_discipline.py")
            if f.rule == "TPS504" and f.symbol == "bad_capture_fresh_array"]
    assert hits and "'table'" in hits[0].message


def test_operand_passing_is_clean():
    assert not [f for f in trace_fixture("trace_discipline.py")
                if f.symbol == "good_pass_as_operand"]


# ---------------------------------------------------------------------------
# TPS601: ledger escape analysis
# ---------------------------------------------------------------------------

def test_ledger_escapes_flagged():
    found = {(f.rule, f.symbol) for f in ledger_fixture("ledger_escape.py")}
    assert ("TPS601", "Engine.bad_await_while_held") in found
    assert ("TPS601", "Engine.bad_raise_while_held") in found
    assert ("TPS601", "Engine.bad_call_while_held") in found


def test_ledger_finding_names_both_sites():
    hits = [f for f in ledger_fixture("ledger_escape.py")
            if f.symbol == "Engine.bad_await_while_held"]
    # Anchored at the acquire (where the sanction goes); the hazard line
    # is named in the message.
    assert hits[0].line == 17 and "(line 18)" in hits[0].message
    assert "SlotArena 'arena'" in hits[0].message


def test_ledger_protection_patterns_clean():
    bad = [f for f in ledger_fixture("ledger_escape.py")
           if "good_" in f.symbol]
    assert not bad, [f.render() for f in bad]


# ---------------------------------------------------------------------------
# TPS101 descends into async generators (satellite of this family)
# ---------------------------------------------------------------------------

def test_async_generator_blocking_flagged():
    from tpuserve.analysis import astlint

    found = astlint.run_paths([FIXTURES / "async_gen.py"], FIXTURES)
    assert any(f.rule == "TPS101" and f.symbol == "Streamer.bad_gen"
               for f in found), [f.render() for f in found]
    assert not [f for f in found if "good_" in f.symbol], \
        [f.render() for f in found]


# ---------------------------------------------------------------------------
# The real tree: both families must hold with an EMPTY baseline — every
# in-repo finding was fixed or carries a reasoned inline sanction.
# ---------------------------------------------------------------------------

def test_repo_tree_clean_for_trace_and_ledger_rules():
    from tpuserve.analysis import astlint
    from tpuserve.analysis.findings import load_baseline

    files = astlint.collect_files([ROOT / "tpuserve"])
    findings = tracelint.run_paths(files, ROOT)
    findings += ledgerlint.run_paths(files, ROOT)
    assert not findings, \
        "TPS5xx/TPS6xx findings in tree:\n" + "\n".join(
            f.render() for f in findings)
    baseline = load_baseline(ROOT / "tpuserve" / "analysis" / "baseline.json")
    assert not baseline, "the TPS5xx/TPS6xx baseline must ship empty"


# ---------------------------------------------------------------------------
# Runtime retrace witness
# ---------------------------------------------------------------------------

@pytest.fixture
def armed_witness():
    witness.force_retrace(True)
    witness.reset_retrace()
    yield
    witness.force_retrace(None)
    witness.reset_retrace()


def test_retrace_registry_semantics(armed_witness):
    # Pre-barrier compiles are warmup: counted, silent.
    witness.note_compile("tg", "b4/float32/none/single")
    witness.declare_warmup_complete()
    # Sanctioned window (lifecycle ensure_compiled): counted, silent.
    with witness.sanctioned_compiles():
        witness.note_compile("tg", "b8/float32/none/single")
    # Anything else after the barrier raises, naming (tag, variant).
    with pytest.raises(witness.RetraceViolation) as ei:
        witness.note_compile("tg", "b16/float32/none/single")
    assert "tag=tg" in str(ei.value)
    assert "b16/float32/none/single" in str(ei.value)
    snap = witness.retrace_snapshot()
    assert snap["enabled"] and snap["barrier_declared"]
    assert snap["warmup_compiles"] == 1
    assert snap["sanctioned_compiles"] == 1
    assert len(snap["violations"]) == 1
    assert snap["violations"][0]["tag"] == "tg"
    assert snap["violations"][0]["variant"] == "b16/float32/none/single"


def test_retrace_witness_end_to_end_on_runtime(armed_witness):
    """A real ModelRuntime: warmup compiles are silent, the clean path
    re-ensures with compile delta 0, and a deliberate post-barrier bucket
    compile raises through the runtime's own compile site."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.runtime import build_runtime

    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1],
                      dtype="float32", num_classes=10, parallelism="single")
    model = build(cfg)
    rt = build_runtime(model)  # warmup: compiles bucket (1,) silently
    witness.declare_warmup_complete()

    before = rt.compiles_total
    assert rt.ensure_compiled() == 0  # steady state: compile delta 0
    assert rt.compiles_total == before
    assert not witness.retrace_snapshot()["violations"]

    # Deliberate retrace: a bucket appears after the barrier.
    cfg.batch_buckets.append(2)
    with pytest.raises(witness.RetraceViolation) as ei:
        rt.ensure_compiled()
    assert "tag=toy" in str(ei.value)
    viol = witness.retrace_snapshot()["violations"][0]
    assert viol["tag"] == "toy"
    assert viol["variant"].split("/")[0] == "2"  # the (2,) bucket
    # The compile counter ticked BEFORE the raise: ledger and witness
    # agree on what happened.
    assert rt.compiles_total == before + 1


def test_retrace_witness_disabled_is_inert():
    witness.force_retrace(False)
    try:
        witness.reset_retrace()
        witness.declare_warmup_complete()
        witness.note_compile("tg", "b4/float32/none/single")  # no raise
        assert witness.retrace_snapshot()["violations"] == []
    finally:
        witness.force_retrace(None)
        witness.reset_retrace()
