"""Parallel ingest (ISSUE 11): [server] ingest_loops SO_REUSEPORT accept
loops, the loop-safe batcher entry, per-loop balance metrics, the
native-decode fallback counter, and the multi-process loadgen merge."""

import asyncio
import json
import socket
import threading
import urllib.request

import numpy as np
import pytest

from tpuserve import frame, preproc
from tpuserve.batcher import QueueFull
from tpuserve.bench.loadgen import (merge_load_summaries, synthetic_frame,
                                    synthetic_frame_pool)
from tpuserve.config import CacheConfig, ModelConfig, ServerConfig, load_config
from tpuserve.server import ServerState, serve_async

EDGE = 8
N_LOOPS = 3


# -- config -------------------------------------------------------------------

def test_ingest_loops_validation():
    with pytest.raises(ValueError, match="ingest_loops"):
        ServerConfig(ingest_loops=0)


def test_ingest_loops_toml(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text('ingest_loops = 3\n[[model]]\nname = "toy"\nfamily = "toy"\n')
    cfg = load_config(str(p))
    assert cfg.ingest_loops == 3
    cfg2 = load_config(str(p), overrides=["ingest_loops=2"])
    assert cfg2.ingest_loops == 2


# -- real multi-loop server ---------------------------------------------------

@pytest.fixture(scope="module")
def multi_loop_server():
    """A REAL serve_async server with 3 accept loops (1 main + 2 ingest
    threads) on an ephemeral SO_REUSEPORT port, driven from this thread
    over plain blocking HTTP (every request a fresh connection, so the
    kernel spreads them across listeners)."""
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("SO_REUSEPORT unavailable")
    cfg = ServerConfig(
        host="127.0.0.1", port=0, ingest_loops=N_LOOPS,
        startup_canary=False, decode_threads=2,
        cache=CacheConfig(enabled=True, capacity=64),
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                            deadline_ms=2.0, dtype="float32", num_classes=10,
                            parallelism="single",
                            request_timeout_ms=10_000.0)],
    )
    state = ServerState(cfg)
    state.build()
    holder = {}
    ready = threading.Event()

    def run_server():
        async def main():
            a_ready = asyncio.Event()
            a_stop = asyncio.Event()
            holder["loop"] = asyncio.get_running_loop()
            holder["stop"] = a_stop
            task = asyncio.ensure_future(serve_async(state, a_ready, a_stop))
            await a_ready.wait()
            ready.set()
            await task

        asyncio.run(main())

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    assert ready.wait(60), "server did not come up"
    port = state.serving_addresses[0][1]
    yield state, f"http://127.0.0.1:{port}"
    holder["loop"].call_soon_threadsafe(holder["stop"].set)
    t.join(30)
    assert not t.is_alive()


def post(base, path, body, ctype):
    req = urllib.request.Request(
        f"{base}{path}", data=body,
        headers={"Content-Type": ctype, "Connection": "close"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def get(base, path):
    req = urllib.request.Request(f"{base}{path}",
                                 headers={"Connection": "close"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read()


def test_every_ingest_loop_serves(multi_loop_server):
    """Fresh-connection requests spread across ALL accept loops; per-loop
    prebound counters prove the balance, and every response is correct no
    matter which loop carried it (the main-loop hop)."""
    state, base = multi_loop_server
    rng = np.random.default_rng(0)
    n = 90
    bodies = [frame.encode_frame(
        [rng.integers(0, 255, (EDGE, EDGE, 3), dtype=np.uint8)
         for _ in range(2)], frame.KIND_RGB8, EDGE) for _ in range(n)]
    oks = 0
    for body in bodies:
        status, raw = post(base, "/v1/models/toy:classify", body,
                           frame.CONTENT_TYPE)
        assert status == 200, raw
        out = json.loads(raw)
        assert len(out["results"]) == 2
        oks += 1
    assert oks == n
    per_loop = [state.ingest[i].requests.value for i in range(N_LOOPS)]
    assert len(state.ingest) == N_LOOPS
    assert sum(per_loop) == n, per_loop
    # 90 fresh connections over 3 SO_REUSEPORT listeners: a silent loop
    # means the spread (or a listener) is broken.
    assert all(v > 0 for v in per_loop), per_loop
    per_loop_bytes = [state.ingest[i].bytes.value for i in range(N_LOOPS)]
    assert sum(per_loop_bytes) == sum(len(b) for b in bodies)


def test_cache_and_stats_work_from_ingest_loops(multi_loop_server):
    """The single-flight cache lives on the main loop; identical framed
    uploads from whatever loop answer identically (the second from cache),
    and /stats (a main-loop-hopped handler) reports the ingest block."""
    state, base = multi_loop_server
    body = synthetic_frame(EDGE, 2, "rgb8", seed=12345)
    hits0 = state.metrics.counter("cache_hits_total{model=toy}").value
    answers = {post(base, "/v1/models/toy:classify", body,
                    frame.CONTENT_TYPE)[1] for _ in range(6)}
    assert len(answers) == 1  # byte-identical regardless of serving loop
    hits1 = state.metrics.counter("cache_hits_total{model=toy}").value
    assert hits1 - hits0 >= 4  # first fills (maybe once per race), rest hit
    status, raw = get(base, "/stats")
    assert status == 200
    stats = json.loads(raw)
    assert set(stats["ingest"]["loops"]) == {str(i) for i in range(N_LOOPS)}
    assert "frame_errors_total" in stats["ingest"]
    assert "native_decode_fallback_total" in stats["ingest"]


def test_malformed_frame_400_from_any_loop(multi_loop_server):
    state, base = multi_loop_server
    for _ in range(6):  # enough fresh connections to land off-main too
        status, raw = post(base, "/v1/models/toy:classify", b"garbage",
                           frame.CONTENT_TYPE)
        assert status == 400, raw
        assert json.loads(raw)["error"].startswith("frame:")


# -- loop-safe batcher entry --------------------------------------------------

def test_submit_threadsafe_from_worker_thread():
    """ModelBatcher.submit_threadsafe: a thread that is NOT the batcher's
    event loop submits and receives the result through a concurrent
    future; QueueFull propagates the same way."""
    from tpuserve.models import build as build_model
    from tpuserve.obs import Metrics
    from tpuserve.runtime import build_runtime
    from tpuserve.batcher import ModelBatcher
    import concurrent.futures as cf

    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                      deadline_ms=2.0, dtype="float32", num_classes=10,
                      parallelism="single", max_queue=4)
    model = build_model(cfg)
    rt = build_runtime(model)
    b = ModelBatcher(model, rt, Metrics(), cf.ThreadPoolExecutor(2))
    item = np.zeros((EDGE, EDGE, 3), dtype=np.uint8)

    async def go():
        await b.start()
        loop = asyncio.get_running_loop()

        def from_thread():
            fut = b.submit_threadsafe(item)
            return fut.result(timeout=10)

        res = await loop.run_in_executor(None, from_thread)
        assert "top_k" in res

        # QueueFull crosses the loop boundary through the future.
        def flood():
            futs = [b.submit_threadsafe(item) for _ in range(64)]
            outcomes = []
            for f in futs:
                try:
                    outcomes.append(f.result(timeout=10))
                except QueueFull:
                    outcomes.append("shed")
            return outcomes

        outcomes = await loop.run_in_executor(None, flood)
        assert any(o == "shed" for o in outcomes)
        assert any(isinstance(o, dict) for o in outcomes)
        await b.stop()

    asyncio.new_event_loop().run_until_complete(go())


def test_submit_threadsafe_before_start_raises():
    from tpuserve.models import build as build_model
    from tpuserve.obs import Metrics
    from tpuserve.runtime import build_runtime
    from tpuserve.batcher import ModelBatcher
    import concurrent.futures as cf

    cfg = ModelConfig(name="toy", family="toy", dtype="float32",
                      num_classes=10, parallelism="single")
    b = ModelBatcher(build_model(cfg), build_runtime(build_model(cfg)),
                     Metrics(), cf.ThreadPoolExecutor(1))
    with pytest.raises(RuntimeError, match="not started"):
        b.submit_threadsafe(np.zeros((EDGE, EDGE, 3), dtype=np.uint8))


# -- native-decode fallback observability -------------------------------------

def test_native_fallback_hook_counts(monkeypatch):
    """decode_image_yuv420 reports every PIL fallback on a native-eligible
    request through the installed hook (the server routes it to
    native_decode_fallback_total{model=})."""
    from tpuserve import native
    from tpuserve.bench.loadgen import synthetic_image_jpeg

    seen = []
    preproc.set_native_fallback_hook(seen.append)
    try:
        monkeypatch.setattr(native, "decode_yuv420",
                            lambda payload, edge: None)
        jpeg = synthetic_image_jpeg(16)
        y, u, v = preproc.decode_image_yuv420(jpeg, "image/jpeg", 16,
                                              model="m1")
        assert y.shape == (16, 16)
        assert seen == ["m1"]  # fallback on a native-eligible request
        # npy bodies never try the native path: no fallback counted.
        arr = np.zeros((16, 16, 3), dtype=np.uint8)
        import io
        buf = io.BytesIO()
        np.save(buf, arr)
        preproc.decode_image_yuv420(buf.getvalue(), "application/x-npy", 16,
                                    model="m1")
        assert seen == ["m1"]
    finally:
        preproc.set_native_fallback_hook(None)


# -- loadgen: frame pools + multi-process merge -------------------------------

def test_synthetic_frame_pool_distinct_and_parseable():
    pool = synthetic_frame_pool(4, edge=16, n_items=3, kind="yuv420")
    assert len(set(pool)) == 4  # distinct bodies
    for body in pool:
        items = frame.parse_frame(body, kind=frame.KIND_YUV420, edge=16,
                                  max_items=8)
        assert len(items) == 3
    # Disjoint seed ranges never collide with the base pool.
    other = synthetic_frame_pool(4, edge=16, n_items=3, kind="yuv420",
                                 seed_base=4)
    assert not set(pool) & set(other)


def test_merge_load_summaries_exact_percentiles():
    parts = [
        {"summary": {"mode": "closed", "n_ok": 3, "n_err": 1, "n_late": 0,
                     "duration_s": 10.0, "throughput_per_s": 30.0,
                     "p50_ms": 1.0, "p90_ms": 1.0, "p99_ms": 1.0,
                     "items_per_request": 8},
         "latencies_ms": [1.0, 2.0, 3.0]},
        {"summary": {"mode": "closed", "n_ok": 3, "n_err": 0, "n_late": 2,
                     "duration_s": 10.0, "throughput_per_s": 40.0,
                     "p50_ms": 100.0, "p90_ms": 100.0, "p99_ms": 100.0},
         "latencies_ms": [100.0, 200.0, 300.0]},
    ]
    out = merge_load_summaries(parts)
    assert out["n_ok"] == 6 and out["n_err"] == 1 and out["n_late"] == 2
    assert out["throughput_per_s"] == 70.0
    assert out["load_workers"] == 2
    assert out["items_per_request"] == 8
    # Exact percentile over the CONCATENATED samples, not an average of
    # the workers' percentiles (which would report ~50 here).
    assert out["p50_ms"] == 3.0
    assert out["p99_ms"] == 300.0


def test_merge_load_summaries_empty():
    with pytest.raises(ValueError):
        merge_load_summaries([])


# -- ingest-aware roofline ----------------------------------------------------

def test_roofline_ingest_phases_and_body_read_ceiling():
    """body_read/parse join the per-phase attribution; body_read is priced
    at the ACTUAL framed request-body bytes against the measured link."""
    from tpuserve.bench import roofline as rl

    latency = {
        "latency_ms{model=m,phase=body_read}": {"n": 10, "p50_ms": 4.0},
        "latency_ms{model=m,phase=parse}": {"n": 10, "p50_ms": 0.05},
        "latency_ms{model=m,phase=compute}": {"n": 10, "p50_ms": 100.0},
    }
    req_bytes = frame.frame_nbytes(frame.KIND_YUV420, 160, 8)
    block = rl.build_roofline(
        latency, "m", buckets=[8], raw_ms_by_bucket={8: 10.0},
        link_mbps=100.0, img_bytes=38400, chip_img_s=None,
        value_img_s=None, req_bytes=req_bytes)
    br = block["phases"]["body_read"]
    assert br["p50_ms"] == 4.0
    assert br["ceiling_kind"] == "wire"
    assert br["ceiling_ms"] == pytest.approx(req_bytes / 100e6 * 1e3,
                                             rel=1e-3)
    assert block["phases"]["parse"]["p50_ms"] == 0.05
    assert block["ingest_req_bytes"] == req_bytes
    # compute still binds here (100 ms >> everything else).
    assert block["binding_phase"] == "compute"
    # Without req_bytes the block is unchanged (back-compat, /stats path).
    naked = rl.build_roofline(
        latency, "m", buckets=[8], raw_ms_by_bucket={8: 10.0},
        link_mbps=100.0, img_bytes=38400, chip_img_s=None, value_img_s=None)
    assert "ingest_req_bytes" not in naked
    assert "ceiling_ms" not in naked["phases"]["body_read"]
