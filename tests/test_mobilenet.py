"""MobileNetV3-Large (config 2): shape/param sanity, replica-mode serving on
the 8-fake-device mesh, HTTP end-to-end. VERDICT.md r2 item 4."""

import asyncio
import io

import jax
import numpy as np
import pytest

from tpuserve.config import ModelConfig, ServerConfig
from tpuserve.models import build

pytestmark = pytest.mark.slow


def mnv3_cfg(**over) -> ModelConfig:
    base = dict(
        name="mnv3", family="mobilenetv3", batch_buckets=[1, 2],
        deadline_ms=2.0, dtype="float32", num_classes=10,
        parallelism="replica", request_timeout_ms=30_000.0,
        image_size=64, wire_size=64,  # small spatial dims: fast CPU compile
    )
    base.update(over)
    return ModelConfig(**base)


def test_module_shapes_and_param_count():
    """Full-size MobileNetV3-Large has ~5.5M params (published figure)."""
    model = build(ModelConfig(name="m", family="mobilenetv3",
                              num_classes=1000, dtype="float32"))
    params = jax.eval_shape(model.init_params, jax.random.key(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    assert 5.3e6 < n < 5.7e6, n


@pytest.fixture(scope="module")
def served():
    from tpuserve.runtime import build_runtime

    model = build(mnv3_cfg())
    rt = build_runtime(model)
    return model, rt


def test_replica_mode_one_executable_per_device(served):
    model, rt = served
    assert rt.mode == "replica"
    assert len(rt.meshes) == len(jax.devices()) == 8
    assert len(rt.executables[(1,)]) == 8


def test_forward_and_round_robin(served):
    model, rt = served
    img = np.random.default_rng(0).integers(0, 255, (1, 64, 64, 3), np.uint8)
    out1 = rt.fetch(rt.run((1,), img))
    out2 = rt.fetch(rt.run((1,), img))  # different replica, same params/seed
    assert out1["probs"].shape == (1, 5)
    np.testing.assert_allclose(out1["probs"], out2["probs"], atol=1e-5)
    assert np.all(np.diff(out1["probs"][0]) <= 1e-7)  # sorted top-k


def test_padding_lanes_inert(served):
    model, rt = served
    img = np.random.default_rng(1).integers(0, 255, (64, 64, 3), np.uint8)
    solo = rt.fetch(rt.run((1,), model.assemble([img], (1,))))
    padded = rt.fetch(rt.run((2,), model.assemble([img], (2,))))
    np.testing.assert_allclose(solo["probs"][0], padded["probs"][0], atol=1e-5)


def test_mobilenet_http_end_to_end():
    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(models=[mnv3_cfg()], decode_threads=2)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            buf = io.BytesIO()
            np.save(buf, np.random.default_rng(0).integers(
                0, 255, (64, 64, 3), dtype=np.uint8))
            resp = await client.post(
                "/v1/models/mnv3:classify", data=buf.getvalue(),
                headers={"Content-Type": "application/x-npy"})
            assert resp.status == 200, await resp.text()
            body = await resp.json()
            assert len(body["top_k"]) == 5
            resp = await client.get("/v1/models")
            inv = await resp.json()
            assert inv["mnv3"]["mode"] == "replica"
            assert inv["mnv3"]["replicas"] == 8
        finally:
            await client.close()

    loop.run_until_complete(go())
    loop.close()


def test_preproc_norm_overridable_per_model():
    """Keras-MobileNetV3 weights expect x/127.5 - 1: preproc_mean/std options
    must reach the fused device preproc (default stays ImageNet stats)."""
    m_default = build(mnv3_cfg())
    m_keras = build(mnv3_cfg(options={"preproc_mean": [0.5, 0.5, 0.5],
                                      "preproc_std": [0.5, 0.5, 0.5]}))
    assert m_default.norm_mean == (0.485, 0.456, 0.406)
    assert m_keras.norm_mean == (0.5, 0.5, 0.5)
    batch = np.full((1, 64, 64, 3), 255, np.uint8)
    x_def = np.asarray(m_default.prepare_batch(batch))
    x_ker = np.asarray(m_keras.prepare_batch(batch))
    np.testing.assert_allclose(x_ker, 1.0, atol=1e-6)  # (1.0 - 0.5) / 0.5
    assert not np.allclose(x_def, x_ker)
