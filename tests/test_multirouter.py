"""Horizontal router tier (ISSUE 13): consistent-hash cache sharding across
N real router processes on one SO_REUSEPORT port.

- pure units: HashRing determinism, ownership balance, and the consistent-
  hashing property (membership churn moves only the leaving member's keys);
- a module-scoped fleet — primary router (in-process) + 1 real peer router
  process + 1 real worker, cache enabled — proving the acceptance
  criteria: byte-identical re-upload through ANY router = exactly 1 worker
  execution, N identical CONCURRENT misses through different routers = 1
  worker execution (cross-router single-flight), owner-router kill
  degrades to local-only with cache_peer_errors_total ticking and ZERO
  5xx, the primary respawns the peer back into the ring, and a fleet
  reload syncs cache generations to every router.

No pytest-asyncio in the image: a module-level event loop drives
everything explicitly (the test_router idiom).
"""

import asyncio
import io
import os
import signal
import time

import numpy as np
import pytest

from tpuserve.config import ModelConfig, RouterConfig, ServerConfig

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning")

NPY = "application/x-npy"


def npy(seed: int = 0, edge: int = 8) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (edge, edge, 3), dtype=np.uint8))
    return buf.getvalue()


@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# ---------------------------------------------------------------------------
# HashRing units
# ---------------------------------------------------------------------------

def test_ring_deterministic_and_total():
    from tpuserve.workerproc.peers import HashRing

    ring = HashRing({0: "a", 1: "b", 2: "c"})
    keys = [f"key{i}" for i in range(200)]
    owners = [ring.owner(k) for k in keys]
    assert owners == [HashRing({0: "a", 1: "b", 2: "c"}).owner(k)
                      for k in keys]
    assert all(o is not None and o[1] in "abc" for o in owners)


def test_ring_balances_ownership():
    from tpuserve.workerproc.peers import HashRing

    ring = HashRing({0: "a", 1: "b", 2: "c"})
    counts = {0: 0, 1: 0, 2: 0}
    for i in range(3000):
        counts[ring.owner(f"key{i}")[0]] += 1
    # vnodes keep every member within a loose band of the fair share.
    assert all(400 <= c <= 1800 for c in counts.values()), counts


def test_ring_membership_churn_moves_only_leavers_keys():
    """The consistent-hashing property the respawn story rests on: when a
    member leaves, keys it did NOT own keep their owner — so a router
    death never reshuffles the survivors' shards."""
    from tpuserve.workerproc.peers import HashRing

    full = HashRing({0: "a", 1: "b", 2: "c"})
    reduced = HashRing({0: "a", 2: "c"})
    moved = stayed = 0
    for i in range(2000):
        k = f"key{i}"
        before = full.owner(k)[0]
        after = reduced.owner(k)[0]
        if before == 1:
            moved += 1
            assert after in (0, 2)
        else:
            assert after == before, k
            stayed += 1
    assert moved > 0 and stayed > 0


def test_ring_empty_owner_none():
    from tpuserve.workerproc.peers import HashRing

    assert HashRing({}).owner("x") is None


# ---------------------------------------------------------------------------
# The 2-router fleet (module-scoped: primary in-process + 1 peer process)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def routers(loop):
    import aiohttp
    from aiohttp import web

    from tpuserve.workerproc.router import (
        RouterState,
        bind_public_socket,
        make_router_app,
    )

    cfg = ServerConfig(
        decode_threads=2, startup_canary=False, drain_timeout_s=3.0,
        watchdog_interval_s=0.2,
        router=RouterConfig(enabled=True, workers=1, routers=2, retry_max=2,
                            health_interval_s=0.2, unhealthy_after=2,
                            respawn_initial_s=0.3, respawn_max_s=2.0,
                            peer_sync_interval_s=0.2),
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=2.0, dtype="float32", num_classes=10,
                            parallelism="single",
                            request_timeout_ms=10_000.0, wire_size=8)],
    )
    cfg.cache.enabled = True
    cfg.cache.capacity = 256
    state = RouterState(cfg)
    sock, port = bind_public_socket("127.0.0.1", 0)
    state.public_addr = ("127.0.0.1", port)
    runner = web.AppRunner(make_router_app(state), access_log=None)

    async def setup():
        await runner.setup()  # on_startup: workers + peer router + ring
        site = web.SockSite(runner, sock)
        await site.start()
        return aiohttp.ClientSession()

    session = loop.run_until_complete(setup())
    base = f"http://127.0.0.1:{port}"

    def run(coro):
        return loop.run_until_complete(coro)

    # Wait for the peer's public listener + complete ring before any test
    # fires concurrent load through both routers.
    async def settle():
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            peer = state.peer_sup.peers.get(1)
            if peer is not None and len(state.ring.members) == 2:
                try:
                    async with session.get(
                            f"{peer.peer_url}/peer/stats") as r:
                        st = await r.json()
                    if st["router"].get("ring", {}).get("size") == 2:
                        return
                except Exception:  # noqa: BLE001 — peer still booting
                    pass
            await asyncio.sleep(0.1)
        raise RuntimeError("peer router never settled into the ring")

    run(settle())
    yield run, session, base, state

    async def teardown():
        await session.close()
        await runner.cleanup()

    loop.run_until_complete(teardown())


async def _worker_requests(session, base) -> float:
    async with session.get(f"{base}/workers/0/metrics") as r:
        assert r.status == 200
        text = await r.text()
    for line in text.splitlines():
        if line.startswith('requests_total{model="toy"}'):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _body_owned_by(state, rid: int, seeds) -> bytes:
    cache = state.caches["toy"]
    for seed in seeds:
        b = npy(seed)
        if state.ring.owner(cache.key_for(("classify", NPY, b)))[0] == rid:
            return b
    raise AssertionError(f"no seed in range owned by router {rid}")


def test_two_routers_serve_one_port(routers):
    run, session, base, state = routers

    async def go():
        assert len(state.ring.members) == 2
        async with session.post(f"{base}/v1/models/toy:classify",
                                data=npy(1),
                                headers={"Content-Type": NPY}) as r:
            assert r.status == 200, await r.text()
        async with session.get(f"{base}/healthz") as r:
            health = await r.json()
        assert health["status"] == "ok", health
        assert health["routers"]["in_ring"] == 2
        # The peer really is a separate router process with its own view.
        peer = state.peer_sup.peers[1]
        async with session.get(f"{peer.peer_url}/peer/stats") as r:
            pstats = await r.json()
        assert pstats["router"]["router_id"] == 1
        assert pstats["router"]["is_primary"] is False
        assert pstats["workers"]["view"] == "peer"
        assert pstats["workers"]["healthy"] == 1

    run(go())


def test_reupload_through_any_router_single_execution(routers):
    """Acceptance: byte-identical re-upload through ANY of N routers =
    exactly 1 worker execution. The primary's dispatch FORWARDS a
    peer-owned key (cache_peer_hops ticks — deterministic, driven through
    the in-process dispatch), the peer's shard holds the one entry, and
    every later upload of the same bytes — whichever router the shared
    port hands it to — hits that entry."""
    from tpuserve.workerproc.router import _dispatch

    run, session, base, state = routers

    async def go():
        body = _body_owned_by(state, 1, range(1000, 1100))
        deadline_at = time.perf_counter() + 10.0
        before = await _worker_requests(session, base)
        hops_before = state.handles["toy"].peer_hops.value

        # First touch THROUGH THE PRIMARY: not the owner -> must forward.
        ans = await _dispatch(state, "toy", "classify", body, NPY,
                              deadline_at)
        assert ans.status == 200
        assert state.handles["toy"].peer_hops.value == hops_before + 1

        # Re-uploads through the shared public port (kernel picks the
        # router) and through the primary again: all hits, same bytes.
        answers = {ans.body}
        for _ in range(2):
            async with session.post(f"{base}/v1/models/toy:classify",
                                    data=body,
                                    headers={"Content-Type": NPY}) as r:
                assert r.status == 200, await r.text()
                answers.add(await r.read())
        ans2 = await _dispatch(state, "toy", "classify", body, NPY,
                               time.perf_counter() + 10.0)
        answers.add(ans2.body)
        assert len(answers) == 1  # byte-identical everywhere
        after = await _worker_requests(session, base)
        assert after - before == 1, \
            (before, after, "re-upload reached a worker twice")

    run(go())


def test_concurrent_misses_across_routers_coalesce(routers):
    """Acceptance: N identical CONCURRENT misses through different routers
    = 1 worker execution — the owner's single-flight leads for the whole
    tier. Two misses enter through the primary's dispatch (forwarded to
    the owner), two through the shared public port."""
    from tpuserve.workerproc.router import _dispatch

    run, session, base, state = routers

    async def go():
        body = _body_owned_by(state, 1, range(2000, 2100))
        before = await _worker_requests(session, base)

        async def post():
            async with session.post(f"{base}/v1/models/toy:classify",
                                    data=body,
                                    headers={"Content-Type": NPY}) as r:
                assert r.status == 200
                return await r.read()

        async def through_primary():
            ans = await _dispatch(state, "toy", "classify", body, NPY,
                                  time.perf_counter() + 10.0)
            assert ans.status == 200
            return ans.body

        results = await asyncio.gather(
            through_primary(), post(), through_primary(), post())
        assert len(set(results)) == 1
        after = await _worker_requests(session, base)
        assert after - before == 1, (before, after)

    run(go())


def test_owner_kill_degrades_local_only_zero_5xx(routers):
    """Acceptance: owner-router kill mid-flight degrades to local with
    cache_peer_errors_total ticking and zero 5xx — then the primary
    respawns the peer back into the ring and forwards resume."""
    from tpuserve.workerproc.router import _dispatch

    run, session, base, state = routers

    async def go():
        peer = state.peer_sup.peers[1]
        errs_before = state.handles["toy"].peer_errors.value
        os.kill(peer.pid, signal.SIGKILL)

        # Peer-owned keys through the primary's dispatch while the ring
        # still names the corpse: every forward fails transport, DEGRADES
        # to the primary's local shard, and answers 200 — zero 5xx
        # attributable to the peer hop, failures counted not surfaced.
        served = 0
        for seed in range(4000, 4400):
            body = npy(seed)
            key = state.caches["toy"].key_for(("classify", NPY, body))
            owner = state.ring.owner(key)
            if owner is None or owner[0] != 1:
                continue  # ring may already have healed: stop the leg
            ans = await _dispatch(state, "toy", "classify", body, NPY,
                                  time.perf_counter() + 10.0)
            assert ans.status == 200, (seed, ans.status, ans.body)
            served += 1
            if served >= 8:
                break
        if served:  # the watchdog may drop the corpse from the ring fast
            assert state.handles["toy"].peer_errors.value > errs_before
        # end to end through the shared port as well: no 5xx ever
        for seed in range(4400, 4410):
            async with session.post(f"{base}/v1/models/toy:classify",
                                    data=npy(seed),
                                    headers={"Content-Type": NPY}) as r:
                await r.read()
                assert r.status == 200

        # supervised recovery: the peer rejoins the ring with a respawn
        # counted, and its replacement serves peer-endpoint traffic again.
        deadline = time.monotonic() + 60.0
        new_peer = None
        while time.monotonic() < deadline:
            new_peer = state.peer_sup.peers.get(1)
            if new_peer is not None and new_peer.pid != peer.pid \
                    and new_peer.proc.is_alive() \
                    and len(state.ring.members) == 2:
                break
            await asyncio.sleep(0.2)
        assert new_peer is not None and new_peer.pid != peer.pid
        assert len(state.ring.members) == 2
        assert state.metrics.counter(
            'router_respawns_total{router=1}').value >= 1
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            try:
                async with session.get(
                        f"{new_peer.peer_url}/peer/healthz") as r:
                    if r.status == 200:
                        break
            except Exception:  # noqa: BLE001 — still booting
                pass
            await asyncio.sleep(0.2)
        async with session.post(
                f"{new_peer.peer_url}/peer/models/toy:classify",
                data=npy(1), headers={"Content-Type": NPY}) as r:
            assert r.status == 200, await r.text()

    run(go())


def test_reload_syncs_generations_to_every_router(routers):
    """A fleet :reload through the shared port bumps the cache generation
    on EVERY router (push + poll), so no router can serve a stale cached
    answer for the old weights."""
    run, session, base, state = routers

    async def go():
        gen_before = state.generations["toy"]
        async with session.post(f"{base}/admin/models/toy:reload") as r:
            info = await r.json()
            assert r.status == 200, info
        assert state.generations["toy"] == gen_before + 1
        peer = state.peer_sup.peers[1]
        deadline = time.monotonic() + 10.0
        pgen = None
        while time.monotonic() < deadline:
            async with session.get(f"{peer.peer_url}/peer/stats") as r:
                pstats = await r.json()
            pgen = pstats["router"]["generations"]["toy"]
            if pgen == state.generations["toy"]:
                break
            await asyncio.sleep(0.2)
        assert pgen == state.generations["toy"], (pgen, state.generations)

    run(go())
