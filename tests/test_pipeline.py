"""Pipeline parallelism (tpuserve.parallel.pipeline) on fake CPU devices.

Correctness bar: GPipe-pipelined stage application must equal applying the
stages sequentially on one device — for a plain MLP stage and for the real
transformer Block the train step uses — and stage params must actually be
sharded one-stage-per-device (the memory point of PP).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuserve.parallel.pipeline import (
    make_stage_mesh,
    pipeline_forward,
    stack_stage_params,
)


def mlp_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _mlp_params(rng, d):
    return {"w": jnp.asarray(rng.normal(size=(d, d)).astype(np.float32) * 0.3),
            "b": jnp.asarray(rng.normal(size=(d,)).astype(np.float32) * 0.1)}


@pytest.mark.slow
@pytest.mark.parametrize("n_stages,n_micro", [(4, 8), (2, 3), (8, 1)])
def test_matches_sequential(n_stages, n_micro):
    rng = np.random.default_rng(0)
    d, mb = 16, 4
    per_stage = [_mlp_params(rng, d) for _ in range(n_stages)]
    xs = jnp.asarray(rng.normal(size=(n_micro, mb, d)).astype(np.float32))

    mesh = make_stage_mesh(n_stages)
    out = pipeline_forward(mlp_stage, stack_stage_params(per_stage), xs, mesh)

    ref = xs
    for p in per_stage:
        ref = jax.vmap(lambda x, p=p: mlp_stage(p, x))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_stage_params_actually_sharded():
    """Each device holds ONE stage's weights — the S-fold memory win."""
    rng = np.random.default_rng(1)
    n_stages, d = 4, 8
    stacked = stack_stage_params([_mlp_params(rng, d) for _ in range(n_stages)])
    mesh = make_stage_mesh(n_stages)
    from jax.sharding import NamedSharding, PartitionSpec as P

    w = jax.device_put(stacked["w"], NamedSharding(mesh, P("stage")))
    assert len(w.addressable_shards) == n_stages
    for shard in w.addressable_shards:
        assert shard.data.shape == (1, d, d)  # one stage per device


@pytest.mark.slow
def test_transformer_block_stage():
    """The real train-step Block pipelines: stage = one pre-LN block."""
    from tpuserve.train import Block, TrainConfig

    cfg = TrainConfig(d_model=16, n_heads=2, d_ff=32, max_seq=8)
    block = Block(cfg)
    rng = np.random.default_rng(2)
    x0 = jnp.asarray(rng.normal(size=(2, 8, 16)).astype(np.float32))
    n_stages = 4
    per_stage = [block.init(jax.random.key(i), x0) for i in range(n_stages)]

    def stage_fn(params, x):
        return block.apply(params, x)

    xs = jnp.stack([x0, x0 + 0.5, x0 - 0.5])  # 3 microbatches
    mesh = make_stage_mesh(n_stages)
    out = pipeline_forward(stage_fn, stack_stage_params(per_stage), xs, mesh)

    ref = xs
    for p in per_stage:
        ref = jax.vmap(lambda x, p=p: block.apply(p, x[None])[0])(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_jit_compiles_one_program():
    """The whole schedule lowers under jit (one XLA program, scan inside)."""
    rng = np.random.default_rng(3)
    per_stage = [_mlp_params(rng, 8) for _ in range(4)]
    mesh = make_stage_mesh(4)
    stacked = stack_stage_params(per_stage)
    xs = jnp.asarray(rng.normal(size=(6, 2, 8)).astype(np.float32))
    jitted = jax.jit(lambda p, x: pipeline_forward(mlp_stage, p, x, mesh))
    np.testing.assert_allclose(np.asarray(jitted(stacked, xs)),
                               np.asarray(pipeline_forward(mlp_stage, stacked, xs, mesh)),
                               atol=1e-6)


def test_too_few_devices_rejected():
    with pytest.raises(ValueError, match="need"):
        make_stage_mesh(99)


def test_stage_count_mismatch_rejected():
    """8 stacked stages on a 4-device axis would silently run every 2nd
    stage via even sharding; must be a loud error instead."""
    rng = np.random.default_rng(4)
    stacked = stack_stage_params([_mlp_params(rng, 8) for _ in range(8)])
    with pytest.raises(ValueError, match="8 stages.*4 devices"):
        pipeline_forward(mlp_stage, stacked,
                         jnp.zeros((2, 2, 8), jnp.float32), make_stage_mesh(4))


@pytest.mark.slow
def test_bert_pipeline_serving_matches_single():
    """parallelism='pipeline' is a SERVING mode, not just a seam
    (VERDICT r4 missing 5): the production runtime compiles BERT over a
    4-stage mesh with stage-sharded trunk params, serves through
    run/fetch, and matches single-device serving bit-for-tolerance. Also
    checks the memory point: every staged leaf is split one-stage-per-
    device, and unsupported families are rejected with guidance."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.runtime import build_runtime

    def cfg(**over):
        base = dict(
            name="bp", family="bert", batch_buckets=[4], seq_buckets=[16],
            dtype="float32", num_classes=4, request_timeout_ms=60_000.0,
            options={"layers": 4, "d_model": 32, "heads": 2, "d_ff": 64,
                     "vocab_size": 512},
        )
        base.update(over)
        return ModelConfig(**base)

    m_s = build(cfg(parallelism="single"))
    rt_s = build_runtime(m_s)
    m_p = build(cfg(parallelism="pipeline", pp=4))
    rt_p = build_runtime(m_p)

    (bucket,) = rt_s.executables
    items = [m_s.host_decode(b'{"text": "pipeline stages over ici"}',
                             "application/json")] * 3
    out_s = rt_s.fetch(rt_s.run(bucket, m_s.assemble(items, bucket)))
    out_p = rt_p.fetch(rt_p.run(bucket, m_p.assemble(items, bucket)))
    np.testing.assert_allclose(out_p["probs"], out_s["probs"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(out_p["indices"][:3], out_s["indices"][:3])

    # One stage's params per device (the reason PP exists).
    staged_leaf = rt_p.params_per_mesh[0]["staged"]["blk0"]["attn"]["query"]["kernel"]
    assert staged_leaf.shape[0] == 4
    assert len(staged_leaf.addressable_shards) >= 4
    for shard in staged_leaf.addressable_shards:
        assert shard.data.shape[0] == 1

    # Families without a homogeneous stack reject with guidance.
    from tpuserve.config import ModelConfig as MC
    toy = build(MC(name="t", family="toy", batch_buckets=[2],
                   num_classes=4, parallelism="pipeline"))
    with pytest.raises(ValueError, match="pipeline"):
        build_runtime(toy)
