"""Pipelined host execution engine (ISSUE 3; tpuserve.hostpipe +
batcher stage pipeline).

Overlap is proven with fake *timed* stages: a runtime whose fetch sleeps a
known duration and a model whose assemble sleeps a known duration, both
recording wall-clock intervals. With depth-k staging, batch N+1's assembly
must run while batch N computes, aggregate stage busy time must exceed
elapsed wall time, arena recycling must never hand out an in-use buffer,
and depth-k dispatch must preserve per-request result mapping and the
PR-2 deadline 504 semantics.
"""

import asyncio
import concurrent.futures as cf
import threading
import time

import numpy as np
import pytest

from tpuserve.batcher import DeadlineExceeded, ModelBatcher
from tpuserve.config import ModelConfig, PipelineConfig
from tpuserve.hostpipe import AssemblyArena, SlotPool, SlotsClosed, StageExecutors
from tpuserve.models import build
from tpuserve.models.base import ServingModel
from tpuserve.obs import PIPELINE_STAGES, Metrics


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class Recorder:
    """Thread-safe (stage, start, end, tag) interval log."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events: list[tuple] = []

    def record(self, stage, t0, t1, tag=None):
        with self._lock:
            self.events.append((stage, t0, t1, tag))

    def intervals(self, stage):
        with self._lock:
            return [(t0, t1, tag) for s, t0, t1, tag in self.events if s == stage]


class FakeModel:
    """Minimal direct-mode model: items are scalar floats, the host batch is
    a (bucket, 4) float32 array whose row 0 column carries the item value.
    Defines assemble_into (alongside assemble) so the batcher takes the
    arena path."""

    def __init__(self, cfg, rec: Recorder, assemble_s=0.0):
        self.cfg = cfg
        self.name = cfg.name
        self.rec = rec
        self.assemble_s = assemble_s

    def bucket_for(self, n, **kw):
        for b in self.cfg.batch_buckets:
            if b >= n:
                return (b,)
        return (self.cfg.batch_buckets[-1],)

    def input_signature(self, bucket):
        import jax

        return jax.ShapeDtypeStruct((bucket[0], 4), np.float32)

    def group_key(self, item):
        return None

    def assemble(self, items, bucket):
        out = np.zeros((bucket[0], 4), np.float32)
        return self.assemble_into(items, bucket, out)

    def assemble_into(self, items, bucket, out):
        t0 = time.perf_counter()
        if self.assemble_s:
            time.sleep(self.assemble_s)
        out[:] = 0
        for i, it in enumerate(items):
            out[i, :] = float(it)
        self.rec.record("assemble", t0, time.perf_counter(),
                        tag=float(items[0]))
        return out

    def host_postprocess(self, outputs, n_valid):
        return [float(outputs[i, 0]) for i in range(n_valid)]


class FakeRuntime:
    """Direct-mode runtime whose fetch (the compute wait) sleeps a
    per-batch duration keyed by the batch's first item value."""

    def __init__(self, rec: Recorder, compute_s=0.1, per_batch=None):
        self.rec = rec
        self.compute_s = compute_s
        self.per_batch = per_batch or {}
        self.n_replicas = 1

    def pick_replica(self):
        return 0

    def run(self, bucket, host_batch, replica=0, params_override=None):
        t0 = time.perf_counter()
        out = np.array(host_batch, copy=True)  # device_put semantics
        self.rec.record("h2d", t0, time.perf_counter(), tag=float(out[0, 0]))
        return out

    def fetch(self, outputs):
        t0 = time.perf_counter()
        tag = float(outputs[0, 0])
        time.sleep(self.per_batch.get(tag, self.compute_s))
        self.rec.record("fetch", t0, time.perf_counter(), tag=tag)
        return outputs


def fake_cfg(**over):
    base = dict(name="fake", family="toy", batch_buckets=[1],
                deadline_ms=5.0, dtype="float32", num_classes=10,
                parallelism="single", max_queue=64, max_inflight=2)
    base.update(over)
    return ModelConfig(**base)


def make_fake_batcher(rec=None, compute_s=0.1, per_batch=None, assemble_s=0.0,
                      pipeline_cfg=None, **cfg_over):
    rec = rec or Recorder()
    cfg = fake_cfg(**cfg_over)
    model = FakeModel(cfg, rec, assemble_s=assemble_s)
    rt = FakeRuntime(rec, compute_s=compute_s, per_batch=per_batch)
    metrics = Metrics()
    pool = cf.ThreadPoolExecutor(max_workers=2)
    b = ModelBatcher(model, rt, metrics, pool, pipeline_cfg=pipeline_cfg)
    return b, metrics, rec


# -- overlap (the tentpole's proof) ------------------------------------------

def test_pipeline_overlaps_assembly_with_compute():
    """Batch N+1's assemble runs while batch N's compute is in flight, and
    aggregate stage busy time exceeds elapsed wall time (the acceptance
    criterion's pipelining proof, at unit scale)."""
    async def go():
        b, metrics, rec = make_fake_batcher(
            compute_s=0.12, assemble_s=0.05,
            pipeline_cfg=PipelineConfig(depth=2, assemble_ahead=2))
        await b.start()
        assert b._use_arena and b.arena is not None
        t0 = time.perf_counter()
        futs = [b.submit(float(i + 1)) for i in range(4)]
        res = await asyncio.wait_for(asyncio.gather(*futs), timeout=10)
        elapsed = time.perf_counter() - t0
        await b.stop()

        assert res == [1.0, 2.0, 3.0, 4.0]
        fetches = rec.intervals("fetch")
        assembles = rec.intervals("assemble")
        assert len(fetches) == 4 and len(assembles) == 4
        busy = sum(t1 - t0 for t0, t1, _ in fetches + assembles)
        # 4 x 0.12 fetch + 4 x 0.05 assemble = 0.68 s of stage time; with
        # depth 2 it must pack into well under the sequential sum.
        assert busy > elapsed, (busy, elapsed)
        assert elapsed < 0.55, elapsed  # sequential would be >= 0.68
        # Direct interval evidence: a later batch's assemble ran
        # concurrently with an earlier batch's compute (>= 20 ms overlap).
        overlapped = any(
            min(a1, fe) - max(a0, fs) > 0.02
            for a0, a1, atag in assembles
            for fs, fe, ftag in fetches
            if atag != ftag
        )
        assert overlapped, (assembles, fetches)

    run(go())


def test_depth_bounds_concurrent_device_batches():
    """depth=1 serializes the device section: fetch intervals never
    overlap each other even though admission allows more batches in."""
    async def go():
        b, _, rec = make_fake_batcher(
            compute_s=0.08,
            pipeline_cfg=PipelineConfig(depth=1, assemble_ahead=3))
        await b.start()
        futs = [b.submit(float(i + 1)) for i in range(3)]
        await asyncio.wait_for(asyncio.gather(*futs), timeout=10)
        await b.stop()
        fetches = sorted(rec.intervals("fetch"))
        for (_, e_prev, _), (s_next, _, _) in zip(fetches, fetches[1:]):
            assert s_next >= e_prev - 1e-4, fetches

    run(go())


def test_depth_k_preserves_result_ordering():
    """Out-of-order completion (batch 1 slow, batch 2 fast) still resolves
    each future with its own request's result."""
    async def go():
        b, _, rec = make_fake_batcher(
            per_batch={1.0: 0.2, 2.0: 0.02, 3.0: 0.02},
            pipeline_cfg=PipelineConfig(depth=2, assemble_ahead=2))
        await b.start()
        futs = [b.submit(float(i + 1)) for i in range(3)]
        res = await asyncio.wait_for(asyncio.gather(*futs), timeout=10)
        await b.stop()
        assert res == [1.0, 2.0, 3.0]
        # The fast batches really did finish before the slow one.
        done_order = [tag for _, _, tag in sorted(rec.intervals("fetch"),
                                                  key=lambda iv: iv[1])]
        assert done_order[-1] == 1.0, done_order

    run(go())


def test_deadline_504_while_waiting_for_staging_slot():
    """PR-2 semantics through the pipelined path: a deadlined request stuck
    behind a slow in-flight batch fails AT its deadline (DeadlineExceeded,
    counted), not when the staging slot finally frees."""
    async def go():
        b, metrics, _ = make_fake_batcher(
            compute_s=0.5,
            pipeline_cfg=PipelineConfig(depth=1, assemble_ahead=4))
        await b.start()
        slow = b.submit(1.0)
        await asyncio.sleep(0.05)  # batch 1 occupies the only staging slot
        t0 = time.perf_counter()
        doomed = b.submit(2.0, deadline_at=t0 + 0.08)
        with pytest.raises(DeadlineExceeded):
            await asyncio.wait_for(doomed, timeout=10)
        waited = time.perf_counter() - t0
        assert waited < 0.35, waited
        assert metrics.counter(
            "deadline_exceeded_total{model=fake}").value == 1
        assert await asyncio.wait_for(slow, timeout=10) == 1.0
        await b.stop()

    run(go())


# -- assembly arena ----------------------------------------------------------

def test_arena_never_hands_out_in_use_buffer():
    rec = Recorder()
    model = FakeModel(fake_cfg(batch_buckets=[4]), rec)
    arena = AssemblyArena(model, slots=2)
    outstanding: set[int] = set()
    lock = threading.Lock()

    def worker(n):
        for _ in range(n):
            lease = arena.acquire((4,))
            with lock:
                assert id(lease.buf) not in outstanding
                outstanding.add(id(lease.buf))
            time.sleep(0.001)
            with lock:
                outstanding.remove(id(lease.buf))
            arena.release(lease)

    threads = [threading.Thread(target=worker, args=(50,)) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert arena.leased == 0
    s = arena.stats()
    assert s["buckets"]["[4]"]["pooled"] <= 2


def test_arena_recycles_and_overflows():
    rec = Recorder()
    model = FakeModel(fake_cfg(batch_buckets=[2]), rec)
    arena = AssemblyArena(model, slots=1)
    a = arena.acquire((2,))
    b = arena.acquire((2,))  # pool exhausted -> overflow allocation
    assert a.pooled and not b.pooled
    assert a.buf is not b.buf
    assert arena.overflow_total == 1
    arena.release(a)
    arena.release(b)  # overflow buffer is NOT pooled
    c = arena.acquire((2,))
    assert c.buf is a.buf  # free-list recycled the pooled buffer
    arena.release(c)
    assert arena.stats()["buckets"]["[2]"]["free"] == 1


def test_batcher_recycles_arena_buffers_end_to_end():
    """Sequential batches reuse pooled buffers (no per-batch allocation) and
    every result is correct despite the reuse."""
    async def go():
        b, _, _ = make_fake_batcher(
            compute_s=0.0,
            pipeline_cfg=PipelineConfig(depth=1, assemble_ahead=0,
                                        arena_slots=1))
        await b.start()
        for i in range(6):
            assert await asyncio.wait_for(
                b.submit(float(i + 10)), timeout=10) == float(i + 10)
        stats = b.arena.stats()
        await b.stop()
        assert stats["overflow_total"] == 0
        assert stats["buckets"]["[1]"]["pooled"] == 1  # one buffer, 6 batches

    run(go())


# -- SlotPool ----------------------------------------------------------------

def test_slotpool_acquire_release():
    async def go():
        p = SlotPool(2)
        s1 = await p.acquire()
        s2 = await p.acquire()
        assert p.in_use == 2 and p.try_acquire() is None
        with pytest.raises(asyncio.TimeoutError):
            await p.acquire(timeout_s=0.02)
        waiter = asyncio.ensure_future(p.acquire())
        await asyncio.sleep(0.01)
        p.release(s1)
        assert await asyncio.wait_for(waiter, timeout=1) == s1
        p.release(s2)

    run(go())


def test_slotpool_close_wakes_waiters():
    async def go():
        p = SlotPool(1)
        await p.acquire()
        waiter = asyncio.ensure_future(p.acquire())
        await asyncio.sleep(0.01)
        p.close()
        with pytest.raises(SlotsClosed):
            await asyncio.wait_for(waiter, timeout=1)
        with pytest.raises(SlotsClosed):
            await p.acquire()

    run(go())


# -- StageExecutors ----------------------------------------------------------

def test_stage_executors_dedicated_pools_and_gauges():
    async def go():
        m = Metrics()
        st = StageExecutors(PipelineConfig(), m)
        try:
            names = {}
            for stage in PIPELINE_STAGES:
                names[stage] = await st.run(
                    "m", stage, lambda: threading.current_thread().name)
            for stage, tname in names.items():
                assert tname.startswith(f"pipe-{stage}"), (stage, tname)
            s = st.stats()
            assert set(s["workers"]) == set(PIPELINE_STAGES)
            assert all(v == 0 for v in s["depth"].values())
            assert all(s["submitted_total"][k] == 1 for k in PIPELINE_STAGES)
            assert m.gauge("pipeline_stage_depth{model=m,stage=h2d}").value == 0
        finally:
            st.shutdown()

    run(go())


# -- assemble_into equivalence ------------------------------------------------

def test_base_assemble_into_matches_assemble():
    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[4],
                      dtype="float32", num_classes=10, parallelism="single")
    model = build(cfg)
    assert type(model).assemble is ServingModel.assemble
    rng = np.random.default_rng(0)
    items = [rng.integers(0, 255, (8, 8, 3), dtype=np.uint8) for _ in range(3)]
    want = model.assemble(items, (4,))
    # Dirty buffer: assemble_into must zero the padded rows, not trust them.
    buf = np.full((4, 8, 8, 3), 7, dtype=np.uint8)
    got = model.assemble_into(items, (4,), buf)
    assert got is buf
    np.testing.assert_array_equal(got, want)


def test_bert_assemble_into_matches_assemble():
    cfg = ModelConfig(
        name="bert", family="bert", batch_buckets=[2], seq_buckets=[8],
        dtype="float32", num_classes=4, parallelism="single",
        options=dict(layers=1, d_model=16, heads=2, d_ff=32, vocab_size=64))
    model = build(cfg)
    items = [np.array([5, 6, 7], np.int32), np.array([9], np.int32)]
    want_ids, want_mask = model.assemble(items, (2, 8))
    buf_ids = np.full((2, 8), 33, np.int32)
    buf_mask = np.full((2, 8), 1, np.int32)
    got_ids, got_mask = model.assemble_into(items, (2, 8), (buf_ids, buf_mask))
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_array_equal(got_mask, want_mask)


def test_custom_assemble_without_assemble_into_skips_arena():
    """A model overriding assemble but not assemble_into must fall back to
    the allocating path (equivalence unprovable)."""
    class Custom(FakeModel):
        def assemble(self, items, bucket):
            return super().assemble(items, bucket)
        assemble_into = ServingModel.assemble_into  # not a real override

    async def go():
        rec = Recorder()
        cfg = fake_cfg()
        model = Custom(cfg, rec)
        b = ModelBatcher(model, FakeRuntime(rec, compute_s=0.0), Metrics(),
                         cf.ThreadPoolExecutor(2))
        await b.start()
        assert not b._use_arena and b.arena is None
        assert await asyncio.wait_for(b.submit(3.0), timeout=10) == 3.0
        await b.stop()

    run(go())


# -- runtime h2d/dispatch split ----------------------------------------------

def test_runtime_h2d_dispatch_split_matches_run():
    from tpuserve.runtime import build_runtime

    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[2],
                      dtype="float32", num_classes=10, parallelism="single")
    model = build(cfg)
    rt = build_runtime(model)
    batch = np.random.default_rng(1).integers(0, 255, (2, 8, 8, 3),
                                              dtype=np.uint8)
    want = rt.fetch(rt.run((2,), batch))
    dev = rt.h2d((2,), batch)
    got = rt.fetch(rt.dispatch((2,), dev))
    np.testing.assert_allclose(got["probs"], want["probs"], rtol=1e-6)
    np.testing.assert_array_equal(got["indices"], want["indices"])


def test_donation_shape_check():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpuserve.runtime import _donation_shapes_ok

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    sh = NamedSharding(mesh, P())
    f32 = lambda shape: jax.ShapeDtypeStruct(shape, np.float32)
    # identity-shaped: every input leaf aliases an output leaf
    assert _donation_shapes_ok(f32((4, 8)), sh, f32((4, 8)), sh)
    # classifier-shaped: input cannot alias the smaller output
    assert not _donation_shapes_ok(f32((4, 8)), sh, f32((4, 3)), sh)
    # two equal inputs, one matching output: only one can alias
    assert not _donation_shapes_ok(
        [f32((4, 8)), f32((4, 8))], sh, [f32((4, 8)), f32((4, 3))], sh)
