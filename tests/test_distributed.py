"""Multi-host seam: host-major mesh grid, DistributedConfig, real
single-process jax.distributed.initialize (SURVEY.md §5 "Distributed comm
backend").

Real multi-host needs multiple processes; what IS testable here: the grid
layout math on stub devices with fake process_index values (the property that
tp/sp blocks never cross a host), config plumbing, the no-op path, and — in a
subprocess, so this process's backend stays untouched — an actual
jax.distributed.initialize handshake with num_processes=1.
"""

import os
import subprocess
import sys
from dataclasses import dataclass
from pathlib import Path

import pytest

from tpuserve.config import DistributedConfig, load_config
from tpuserve.parallel import host_major_grid, init_distributed, make_mesh
from tpuserve.parallel.mesh import MeshPlan


@dataclass(frozen=True)
class FakeDev:
    id: int
    process_index: int


def _devs(n_hosts: int, per_host: int) -> list[FakeDev]:
    return [FakeDev(id=h * per_host + i, process_index=h)
            for h in range(n_hosts) for i in range(per_host)]


def test_grid_single_host_is_plain_reshape():
    devs = _devs(1, 8)
    grid = host_major_grid(devs, dp=2, tp=2, sp=2)
    assert grid.shape == (2, 2, 2)
    assert [d.id for d in grid.reshape(-1)] == list(range(8))


def test_grid_tp_sp_blocks_stay_on_one_host():
    # 4 hosts x 4 devices, tp=2 sp=2 -> each dp row must be one host's block.
    devs = _devs(4, 4)
    grid = host_major_grid(devs, dp=4, tp=2, sp=2)
    for dp_row in grid:
        hosts = {d.process_index for d in dp_row.reshape(-1)}
        assert len(hosts) == 1, f"tp/sp block crosses hosts: {hosts}"


def test_grid_data_axis_is_host_major():
    devs = _devs(2, 8)  # 2 hosts x 8 -> dp=4 with tp=2 sp=2
    grid = host_major_grid(devs, dp=4, tp=2, sp=2)
    row_hosts = [grid[i, 0, 0].process_index for i in range(4)]
    assert row_hosts == sorted(row_hosts), "data axis must walk hosts in rank order"


def test_grid_rejects_tp_sp_crossing_dcn():
    devs = _devs(4, 2)  # 2 devices per host cannot hold tp*sp=4
    with pytest.raises(ValueError, match="must divide each host"):
        host_major_grid(devs, dp=2, tp=2, sp=2)


def test_grid_rejects_ragged_hosts():
    devs = _devs(2, 4) + [FakeDev(id=99, process_index=2)]
    with pytest.raises(ValueError, match="unequal"):
        host_major_grid(devs, dp=9, tp=1, sp=1)


def test_make_mesh_still_builds_on_real_fake_devices():
    # The host-major path is the identity for single-host: existing meshes
    # (8 fake CPU devices, all process_index 0) keep working.
    mesh = make_mesh(MeshPlan(tp=2, sp=2))
    assert dict(mesh.shape) == {"data": 2, "model": 2, "seq": 2}


def test_init_distributed_disabled_is_noop():
    assert init_distributed(DistributedConfig()) is False


def test_distributed_config_from_toml(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text(
        'port = 9999\n\n[distributed]\ncoordinator_address = "10.0.0.1:8476"\n'
        "num_processes = 4\nprocess_id = 2\n"
    )
    cfg = load_config(str(p))
    assert cfg.distributed.coordinator_address == "10.0.0.1:8476"
    assert cfg.distributed.num_processes == 4
    assert cfg.distributed.process_id == 2
    # default stays disabled
    assert load_config(None).distributed.coordinator_address == ""


def _cpu_subprocess_env(fake_devices: int | None = None) -> dict:
    """Env for child JAX processes that must stay on fake CPU devices.

    The dev box's sitecustomize re-registers the tunneled-TPU platform
    (overriding JAX_PLATFORMS) whenever PALLAS_AXON_POOL_IPS is set, so it
    must be absent from the child env."""
    repo_root = str(Path(__file__).resolve().parents[1])
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if fake_devices:
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={fake_devices}"
    return env


@pytest.mark.slow
def test_two_process_collectives_across_the_dcn_seam():
    """The real thing, minus the hardware: two OS processes (4 fake CPU
    devices each) form one 8-device jax.distributed cluster through
    init_distributed, build the host-major (data, model, seq) mesh, and a
    jitted global reduction crosses the process boundary — the exact
    topology a 2-host TPU pod serves with, DCN seam included."""
    port = 17000 + os.getpid() % 2000
    code = (
        "import sys\n"
        "rank = int(sys.argv[1])\n"
        "import jax, jax.numpy as jnp\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "from tpuserve.config import DistributedConfig\n"
        "from tpuserve.parallel import init_distributed, make_mesh, process_info\n"
        "from tpuserve.parallel.mesh import MeshPlan\n"
        f"cfg = DistributedConfig(coordinator_address='127.0.0.1:{port}',"
        " num_processes=2, process_id=rank)\n"
        "assert init_distributed(cfg) is True\n"
        "info = process_info()\n"
        "assert (info['process_count'], info['global_devices']) == (2, 8), info\n"
        "mesh = make_mesh(MeshPlan(tp=2))\n"
        "for block in mesh.devices.reshape(-1, 2):\n"
        "    hosts = {d.process_index for d in block}\n"
        "    assert len(hosts) == 1, f'tp block crosses hosts: {hosts}'\n"
        "sh = NamedSharding(mesh, P('data'))\n"
        "y = jax.jit(lambda: jnp.arange(8.0), out_shardings=sh)()\n"
        "total = jax.jit(jnp.sum)(y)  # cross-process (DCN-seam) reduction\n"
        "print(f'RANK{rank} OK total={float(total)} "
        "hosts={len(set(d.process_index for d in jax.devices()))}')\n"
    )
    env = _cpu_subprocess_env(fake_devices=4)
    # File-backed output: draining two interdependent children through pipes
    # sequentially can deadlock on a full pipe buffer mid-handshake.
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        logs = [open(f"{td}/rank{r}.log", "w+") for r in range(2)]
        procs = [subprocess.Popen([sys.executable, "-c", code, str(r)],
                                  stdout=logs[r], stderr=subprocess.STDOUT,
                                  text=True, env=env) for r in range(2)]
        try:
            for p in procs:
                p.wait(timeout=180)
        finally:
            for p in procs:  # reap stragglers so no orphan holds the port
                if p.poll() is None:
                    p.kill()
                    p.wait()
            outs = []
            for lg in logs:
                lg.seek(0)
                outs.append(lg.read())
                lg.close()
        if any("Multiprocess computations aren't implemented on the CPU"
               in out for out in outs):
            pytest.skip("this jaxlib's CPU backend lacks multi-process "
                        "collectives; the DCN-seam check needs a newer jax "
                        "or real hardware")
        for r, out in enumerate(outs):
            assert f"RANK{r} OK total=28.0 hosts=2" in out, (r, out[-2000:])


@pytest.mark.slow
def test_real_initialize_single_process_subprocess():
    """jax.distributed.initialize actually handshakes (1-process cluster).

    Runs in a subprocess because initialize() must precede backend init and
    this test process's backend is already up.
    """
    port = 18000 + os.getpid() % 2000  # avoid collisions across parallel runs
    code = (
        "import jax\n"
        "from tpuserve.config import DistributedConfig\n"
        "from tpuserve.parallel import init_distributed, process_info\n"
        f"cfg = DistributedConfig(coordinator_address='127.0.0.1:{port}',"
        " num_processes=1, process_id=0)\n"
        "assert init_distributed(cfg) is True\n"
        "info = process_info()\n"
        "assert info['process_count'] == 1, info\n"
        "assert info['global_devices'] >= 1, info\n"
        "print('DIST_OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120, env=_cpu_subprocess_env(),
    )
    assert "DIST_OK" in out.stdout, out.stderr[-2000:]


def test_process_info_single_host_facts():
    """ISSUE 13 satellite: process_info() is the multi-machine seam's
    introspection — exercised BEFORE anyone needs a pod. Single-process:
    rank 0 of 1, local == global devices, a real platform string."""
    from tpuserve.parallel import process_info

    info = process_info()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert info["global_devices"] == info["local_devices"] >= 1
    assert info["platform"] in ("cpu", "tpu", "gpu")


def test_init_distributed_pins_only_explicit_coordinates(monkeypatch):
    """init_distributed forwards exactly the coordinates the config pins:
    -1 means 'let jax read the cluster environment' and must NOT be
    passed through."""
    import tpuserve.parallel.distributed as dist

    calls = []
    monkeypatch.setattr(dist.jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(dist.jax, "process_index", lambda: 0)
    monkeypatch.setattr(dist.jax, "process_count", lambda: 1)

    assert dist.init_distributed(
        DistributedConfig(coordinator_address="h:1")) is True
    assert calls[-1] == {"coordinator_address": "h:1"}

    assert dist.init_distributed(DistributedConfig(
        coordinator_address="h:1", num_processes=4, process_id=2)) is True
    assert calls[-1] == {"coordinator_address": "h:1",
                         "num_processes": 4, "process_id": 2}


def test_stats_topology_block_over_http():
    """ISSUE 13 satellite: process_info() is wired into the server's
    /stats as the `topology` block, so every worker behind the router tier
    reports its process coordinates next to its serving state."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.config import ModelConfig, ServerConfig
    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1],
                            deadline_ms=2.0, dtype="float32", num_classes=10,
                            parallelism="single")],
        decode_threads=2, startup_canary=False)
    state = ServerState(cfg)
    state.build()
    state.worker_id = 7  # what worker_main stamps behind the router tier

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            resp = await client.get("/stats")
            assert resp.status == 200
            topo = (await resp.json())["topology"]
            assert topo["process_index"] == 0
            assert topo["process_count"] == 1
            assert topo["worker_id"] == 7
            assert topo["distributed"] is False
            assert topo["platform"] in ("cpu", "tpu", "gpu")
        finally:
            await client.close()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()
