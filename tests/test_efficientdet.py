"""EfficientDet-D0 (config 4): fixed-shape NMS vs a naive reference,
padded-lane invariance, detect HTTP end-to-end. VERDICT.md r2 item 4;
SURVEY.md §3f, §7 hard part 4."""

import asyncio
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuserve.config import ModelConfig, ServerConfig
from tpuserve.models import build
from tpuserve.models.efficientdet import (
    decode_boxes, fixed_nms, make_anchors, pairwise_iou)

pytestmark = pytest.mark.slow


def det_cfg(**over) -> ModelConfig:
    base = dict(
        name="det", family="efficientdet", batch_buckets=[1, 2],
        deadline_ms=2.0, dtype="float32", parallelism="single",
        request_timeout_ms=60_000.0, image_size=64, wire_size=64,
        options=dict(det_classes=5, fpn_channels=16, fpn_repeats=1,
                     head_repeats=1, max_level=5, pre_nms=32, max_dets=8,
                     backbone_width=0.25, backbone_depth=0.35,
                     score_thresh=0.005),
    )
    base.update(over)
    return ModelConfig(**base)


def test_full_size_matches_published_figures():
    """EfficientDet-D0: ~3.9M params, 49104 anchors at 512px (published)."""
    m = build(ModelConfig(name="d0", family="efficientdet", dtype="float32",
                          image_size=512, wire_size=512))
    p = jax.eval_shape(m.init_params, jax.random.key(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(p))
    assert 3.7e6 < n < 4.1e6, n
    assert m.anchors.shape == (49104, 4)


def test_anchor_table_matches_network_for_indivisible_sizes():
    """SAME-padded stride-2 stacks produce ceil-sized feature maps; the anchor
    grid must match even when image_size % 2**max_level != 0 (review fix)."""
    m = build(det_cfg(image_size=100, wire_size=100))
    shapes = jax.eval_shape(m.module.apply, m.init_params(jax.random.key(0)),
                            jax.ShapeDtypeStruct((1, 100, 100, 3), jnp.float32))
    assert shapes[0].shape[1] == m.anchors.shape[0]


def naive_nms(boxes, scores, classes, max_dets, iou_t, score_t):
    """Greedy per-class NMS in plain numpy: the semantic reference."""
    def iou(a, b):
        ymin = max(a[0], b[0]); xmin = max(a[1], b[1])
        ymax = min(a[2], b[2]); xmax = min(a[3], b[3])
        inter = max(ymax - ymin, 0) * max(xmax - xmin, 0)
        area = lambda t: max(t[2] - t[0], 0) * max(t[3] - t[1], 0)  # noqa: E731
        u = area(a) + area(b) - inter
        return inter / u if u > 0 else 0.0

    order = np.argsort(-scores, kind="stable")
    kept = []
    for i in order:
        if scores[i] <= score_t or len(kept) == max_dets:
            break
        if any(classes[i] == classes[j] and iou(boxes[i], boxes[j]) > iou_t
               for j in kept):
            continue
        kept.append(int(i))
    return kept


def test_fixed_nms_matches_naive_reference(rng):
    k, max_dets, iou_t, score_t = 64, 16, 0.5, 0.05
    yx = rng.uniform(0, 0.8, (k, 2))
    hw = rng.uniform(0.05, 0.3, (k, 2))
    boxes = np.concatenate([yx, yx + hw], axis=-1).clip(0, 1).astype(np.float32)
    scores = rng.uniform(0, 1, (k,)).astype(np.float32)
    classes = rng.integers(0, 3, (k,)).astype(np.int32)

    out = jax.jit(lambda b, s, c: fixed_nms(b, s, c, max_dets, iou_t, score_t))(
        boxes, scores, classes)
    ref = naive_nms(boxes, scores, classes, max_dets, iou_t, score_t)

    n = int(out["n"])
    assert n == len(ref)
    # Same boxes in the same (score-descending) order.
    np.testing.assert_allclose(np.asarray(out["boxes"])[:n], boxes[ref], atol=1e-6)
    np.testing.assert_array_equal(np.asarray(out["classes"])[:n], classes[ref])
    # Invalid slots are marked class -1, score 0.
    assert (np.asarray(out["classes"])[n:] == -1).all()
    assert (np.asarray(out["scores"])[n:] == 0).all()


def test_pairwise_iou_basics():
    boxes = np.array([[0, 0, 1, 1], [0, 0, 1, 1], [0.5, 0.5, 1.5, 1.5],
                      [2, 2, 3, 3]], np.float32)
    iou = np.asarray(pairwise_iou(jnp.asarray(boxes)))
    assert iou[0, 1] == pytest.approx(1.0)
    assert iou[0, 2] == pytest.approx(0.25 / 1.75, abs=1e-6)
    assert iou[0, 3] == 0.0


def test_decode_boxes_identity_and_scale():
    anchors = jnp.asarray(make_anchors(64, 3, 3)[:4])
    reg = jnp.zeros((4, 4))
    boxes = np.asarray(decode_boxes(reg, anchors, 64))
    a = np.asarray(anchors)
    np.testing.assert_allclose(
        boxes[:, 2] - boxes[:, 0],
        np.clip((a[:, 0] + a[:, 2] / 2) / 64, 0, 1)
        - np.clip((a[:, 0] - a[:, 2] / 2) / 64, 0, 1), atol=1e-6)
    # log-scale: th = ln 2 doubles the (unclipped) height
    reg2 = reg.at[:, 2].set(np.log(2.0))
    b2 = np.asarray(decode_boxes(reg2, anchors, 64))
    assert (b2[:, 2] - b2[:, 0] >= boxes[:, 2] - boxes[:, 0] - 1e-6).all()


@pytest.fixture(scope="module")
def det_model():
    m = build(det_cfg())
    return m, m.init_params(jax.random.key(0)), jax.jit(m.forward)


def test_padded_lanes_do_not_affect_real_lanes(det_model, rng):
    m, params, fwd = det_model
    img = rng.integers(0, 255, (64, 64, 3), np.uint8)
    other = rng.integers(0, 255, (64, 64, 3), np.uint8)
    b1 = m.assemble([img], (2,))                 # zero-padded lane 1
    b2 = m.assemble([img, other], (2,))
    o1 = jax.tree_util.tree_map(np.asarray, fwd(params, b1))
    o2 = jax.tree_util.tree_map(np.asarray, fwd(params, b2))
    for k in ("boxes", "scores", "classes", "n"):
        np.testing.assert_allclose(o1[k][0], o2[k][0], atol=1e-5, err_msg=k)


def test_host_postprocess_shapes(det_model, rng):
    m, params, fwd = det_model
    img = rng.integers(0, 255, (64, 64, 3), np.uint8)
    out = jax.tree_util.tree_map(np.asarray, fwd(params, m.assemble([img], (2,))))
    res = m.host_postprocess(out, 1)
    assert len(res) == 1
    assert res[0]["num_detections"] == len(res[0]["detections"])
    for d in res[0]["detections"]:
        assert len(d["box"]) == 4
        assert 0 <= d["class"] < 5
        assert d["score"] > 0


def test_sharded_dp_matches_single_device(rng):
    """Detection served sharded over the 8-fake-device data axis must produce
    the same results as an unsharded jit of the same params (SURVEY §2.1)."""
    from tpuserve.runtime import build_runtime

    m = build(det_cfg(parallelism="sharded", batch_buckets=[8]))
    rt = build_runtime(m)
    assert rt.mode == "sharded"
    imgs = [rng.integers(0, 255, (64, 64, 3), np.uint8) for _ in range(5)]
    batch = m.assemble(imgs, (8,))
    np_out = rt.fetch(rt.run((8,), batch))

    ref = jax.tree_util.tree_map(
        np.asarray, jax.jit(m.forward)(rt.params_per_mesh[0], batch))
    for k in ("boxes", "scores", "classes", "n"):
        np.testing.assert_allclose(np.asarray(np_out[k])[:5], ref[k][:5],
                                   atol=1e-5, err_msg=k)


def test_http_detect_end_to_end():
    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(models=[det_cfg()], decode_threads=2,
                       startup_canary=False)
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()
    try:
        async def run():
            client = TestClient(TestServer(app))
            await client.start_server()
            buf = io.BytesIO()
            np.save(buf, np.random.default_rng(0).integers(
                0, 255, (64, 64, 3), dtype=np.uint8))
            r = await client.post("/v1/models/det:detect", data=buf.getvalue(),
                                  headers={"Content-Type": "application/x-npy"})
            body = await r.json()
            await client.close()
            return r.status, body

        status, body = loop.run_until_complete(run())
        assert status == 200, body
        assert "detections" in body and "num_detections" in body
    finally:
        loop.close()
