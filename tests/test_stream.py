"""Fail-safe streaming semantics (ISSUE 17): SSE/frame wire goldens, the
one-terminal contract end-to-end over HTTP with a byte audit against the
unary result, disconnect-frees-slot, the stream-drain budget, the shared
``?stream=`` validator, and the torn-stream parser tolerance the
"stream_stall" / "stream_disconnect" fault kinds exercise in the drill.
docs/ROBUSTNESS.md "Streaming failure semantics"."""

import asyncio
import json

import pytest

from tpuserve.bench.loadgen import SseParser
from tpuserve.config import (FAULT_KINDS, GenserveConfig, ModelConfig,
                             ServerConfig)
from tpuserve.frame import StreamFrameReader, encode_stream_event
from tpuserve.genserve import GenEngine
from tpuserve.models import build
from tpuserve.obs import Metrics
from tpuserve.runtime import build_runtime

TG_OPTS = dict(layers=1, d_model=32, heads=2, d_ff=64, vocab_size=512,
               prompt_len=16, max_new_tokens=64)


def tg_cfg(**over) -> ModelConfig:
    base = dict(name="tg", family="textgen", batch_buckets=[1, 2, 4],
                dtype="float32", parallelism="single", max_queue=64,
                request_timeout_ms=60_000.0, options=dict(TG_OPTS))
    base.update(over)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def tg_rt():
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    eng = GenEngine(model, rt, Metrics(), GenserveConfig(slots=4))
    eng.compile()
    return model, rt


def make_engine(tg_rt, metrics=None, slots=4, **gc_over):
    model, rt = tg_rt
    m = metrics or Metrics()
    eng = GenEngine(model, rt, m, GenserveConfig(slots=slots, **gc_over))
    eng.compile()
    return eng, m


def prompt_item(model, prompt="hello world", seed=0, max_new=8):
    body = {"prompt": prompt, "seed": seed, "max_new_tokens": max_new}
    return model.host_decode(json.dumps(body).encode(), "application/json")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def drain_stream(stream, timeout_s=30.0):
    """Consume a GenStream to its terminal; the one-terminal contract says
    this always returns (every failure path enqueues a terminal)."""
    units = []
    deadline = asyncio.get_running_loop().time() + timeout_s
    while True:
        budget = deadline - asyncio.get_running_loop().time()
        assert budget > 0, f"no terminal within {timeout_s}s: {units}"
        unit = await asyncio.wait_for(stream.get(), budget)
        units.append(unit)
        if unit["type"] in ("done", "error"):
            return units


# ---------------------------------------------------------------------------
# Wire goldens
# ---------------------------------------------------------------------------

def test_sse_wire_goldens(tg_rt):
    """The SSE encoding is a wire contract: event name = unit type, data =
    the unit minus transport-internal keys, blank-line terminated."""
    model, _ = tg_rt
    token = model.encode_stream_unit(
        {"type": "token", "text": "hi", "index": 3})
    assert token == (b"event: token\n"
                     b'data: {"text": "hi", "index": 3}\n\n')
    done = model.encode_stream_unit(
        {"type": "done", "finish_reason": "stop",
         "usage": {"completion_tokens": 6}})
    assert done.startswith(b"event: done\ndata: ")
    assert done.endswith(b"\n\n")
    assert json.loads(done.split(b"data: ", 1)[1]) == {
        "finish_reason": "stop", "usage": {"completion_tokens": 6}}
    # droppable is transport metadata (slow-consumer policy), never wire.
    prog = model.encode_stream_unit(
        {"type": "progress", "step": 2, "droppable": True})
    assert b"droppable" not in prog
    assert model.stream_heartbeat() == b": hb\n\n"  # SSE comment frame
    assert model.stream_content_type() == "text/event-stream"


def test_frame_stream_event_roundtrip():
    """Binary stream events (sd15's wire) survive arbitrary chunk tears:
    StreamFrameReader reassembles, .pending flags a torn tail."""
    a = encode_stream_event(json.dumps({"type": "progress",
                                        "step": 1}).encode())
    b = encode_stream_event(json.dumps({"type": "done",
                                        "finish_reason": "stop"}).encode())
    blob = a + b
    for cut in range(1, len(blob)):
        r = StreamFrameReader()
        events = list(r.feed(blob[:cut])) + list(r.feed(blob[cut:]))
        payloads = [json.loads(p) for _, p in events
                    if p is not None]
        assert {"type": "progress", "step": 1} in payloads
        assert payloads[-1]["type"] == "done"
        assert not r.pending  # fully consumed
    r = StreamFrameReader()
    list(r.feed(blob[:len(a) + 3]))
    assert r.pending  # torn mid-frame: the tail is visible, not silent


def test_sse_parser_torn_event_tolerance():
    """A SIGKILL tears an SSE stream mid-event; the router glues its error
    terminal right after. The parser must never let the torn fragment
    swallow the terminal — it surfaces as junk instead."""
    p = SseParser()
    events = list(p.feed(b'event: token\ndata: {"text": "a", "index": 0}'
                         b"\n\n"))
    # torn token event (no blank line) + the router's appended terminal:
    events += list(p.feed(b'event: token\ndata: {"te'
                          b'\nevent: error\ndata: {"error": '
                          b'"upstream_error", "message": "worker died"}'
                          b"\n\n"))
    kinds = [e for e, _ in events]
    assert kinds == ["token", "token", "error"]
    assert json.loads(events[-1][1])["error"] == "upstream_error"
    with pytest.raises(json.JSONDecodeError):
        json.loads(events[1][1])  # the torn fragment is the junk one
    assert not p.pending


# ---------------------------------------------------------------------------
# Engine: one-terminal contract, disconnect, drain budget
# ---------------------------------------------------------------------------

def test_stream_happy_path_one_terminal(tg_rt):
    model, _ = tg_rt
    eng, m = make_engine(tg_rt)

    async def go():
        await eng.start()
        try:
            fut, stream = eng.submit_stream(
                prompt_item(model, "stream me", seed=9, max_new=6))
            units = await drain_stream(stream)
            terminal = units[-1]
            assert terminal["type"] == "done"
            assert terminal["finish_reason"] in ("stop", "length")
            assert terminal["usage"]["completion_tokens"] == 6
            tokens = [u for u in units if u["type"] == "token"]
            assert [u["index"] for u in tokens] == list(range(len(tokens)))
            # Byte audit: streamed deltas concatenate to the unary text
            # (detokenize is append-only; generation is seeded).
            result = await fut
            assert "".join(u["text"] for u in tokens) == result["text"]
            assert sum(1 for u in units
                       if u["type"] in ("done", "error")) == 1
        finally:
            await eng.stop()
        assert m.counter("gen_streams_total{model=tg}").value == 1
        assert m.counter(
            "gen_stream_terminated_total{model=tg,reason=done}").value == 1

    run(go())


def test_disconnect_frees_slot_and_ledger_balances(tg_rt):
    """A client disconnect (cancelled future + closed stream — exactly
    what the HTTP layer's abandon hook does) must free the slot for
    fold-in and tick gen_client_disconnects_total; the arena ledger ends
    balanced."""
    model, _ = tg_rt
    eng, m = make_engine(tg_rt)

    async def go():
        await eng.start()
        try:
            fut, stream = eng.submit_stream(
                prompt_item(model, "abandoned", seed=3, max_new=64))
            first = await asyncio.wait_for(stream.get(), 30.0)
            assert first["type"] == "token"
            fut.cancel()
            stream.close()
            deadline = asyncio.get_running_loop().time() + 30.0
            while eng.arena.n_active:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            assert eng.arena.n_free == eng.slots  # ledger balanced
            assert m.counter(
                "gen_client_disconnects_total{model=tg}").value == 1
            assert m.counter(
                "gen_stream_terminated_total{model=tg,"
                "reason=disconnect}").value == 1
        finally:
            await eng.stop()

    run(go())


def test_stream_drain_budget_terminates_stragglers(tg_rt):
    """Drain gives in-flight streams a bounded budget (stream_drain_s);
    past it they get the well-formed "drain" error terminal — never a
    silent truncation, never an unbounded drain."""
    from tpuserve.faults import FaultInjector

    model, _ = tg_rt
    eng, m = make_engine(tg_rt, stream_drain_s=0.05)
    # Slow each iteration so the generation provably outlives the 50 ms
    # stream budget on any host.
    eng.injector = FaultInjector.single("slow_dispatch", delay_ms=20.0)

    async def go():
        await eng.start()
        try:
            fut, stream = eng.submit_stream(
                prompt_item(model, "long haul", seed=5, max_new=64))
            first = await asyncio.wait_for(stream.get(), 30.0)
            assert first["type"] == "token"
            loop = asyncio.get_running_loop()
            ok = await eng.drain(loop.time() + 30.0)
            assert ok, "drain must converge once stragglers are killed"
            units = await drain_stream(stream, timeout_s=5.0)
            terminal = units[-1]
            assert terminal["type"] == "error"
            assert terminal["error"] == "drain"
            assert fut.done()
            assert m.counter(
                "gen_stream_terminated_total{model=tg,"
                "reason=drain}").value == 1
        finally:
            await eng.stop()

    run(go())


def test_shutdown_terminates_streams(tg_rt):
    """stop() mid-generation pushes the "shutdown" error terminal. The
    tiny stream queue guarantees the step loop is still mid-flight
    (blocked emitting into the full queue) when stop lands — the
    terminal can't race a natural "done"."""
    model, _ = tg_rt
    eng, m = make_engine(tg_rt, stream_queue=4)

    async def go():
        await eng.start()
        fut, stream = eng.submit_stream(
            prompt_item(model, "cut off", seed=8, max_new=64))
        await asyncio.wait_for(stream.get(), 30.0)
        await eng.stop()
        units = await drain_stream(stream, timeout_s=5.0)
        assert units[-1]["type"] == "error"
        assert units[-1]["error"] == "shutdown"
        assert m.counter("gen_stream_terminated_total{model=tg,"
                         "reason=shutdown}").value == 1

    run(go())


# ---------------------------------------------------------------------------
# HTTP front door: SSE end-to-end, validator, injected tears
# ---------------------------------------------------------------------------

def _gen_server(**over):
    from tpuserve.server import ServerState

    base = dict(
        decode_threads=2,
        genserve=GenserveConfig(enabled=True, slots=4),
        models=[tg_cfg()])
    base.update(over)
    cfg = ServerConfig(**base)
    state = ServerState(cfg)
    state.build()
    return state


def test_http_stream_end_to_end_byte_audited():
    """stream=true over HTTP: the committed response carries the first-
    byte latch header, exactly one done terminal with finish reason +
    usage, contiguous token indices, and the concatenated deltas equal
    the unary result byte-for-byte (the drill's audit anchor)."""
    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.server import make_app

    state = _gen_server()
    body = json.dumps({"prompt": "stream parity", "seed": 11,
                       "max_new_tokens": 8})
    hdr = {"Content-Type": "application/json"}

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            unary = await client.post("/v1/models/tg:generate",
                                      data=body, headers=hdr)
            assert unary.status == 200, await unary.text()
            ref = await unary.json()

            r = await client.post("/v1/models/tg:generate?stream=true",
                                  data=body, headers=hdr)
            assert r.status == 200, await r.text()
            assert r.headers["X-Tpuserve-Stream"] == "1"  # the latch
            assert r.headers["Content-Type"].startswith("text/event-stream")
            events = list(SseParser().feed(await r.read()))
            tokens = [json.loads(d) for e, d in events if e == "token"]
            terminals = [(e, json.loads(d)) for e, d in events
                         if e in ("done", "error")]
            assert len(terminals) == 1 and terminals[0][0] == "done"
            assert terminals[0][1]["finish_reason"] in ("stop", "length")
            assert terminals[0][1]["usage"]["completion_tokens"] == 8
            assert [t["index"] for t in tokens] == list(range(len(tokens)))
            assert "".join(t["text"] for t in tokens) == ref["text"]

            metrics = await (await client.get("/metrics")).text()
            assert 'gen_streams_total{model="tg"}' in metrics
            assert 'gen_first_unit_ms' in metrics
        finally:
            await client.close()

    run(go())


def test_http_junk_stream_flag_rejects():
    """A typo'd ?stream= must 400 loudly (shared validator — the router
    imports the same _requested_stream), never silently serve unary."""
    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.server import _requested_stream, make_app

    state = _gen_server()
    body = json.dumps({"prompt": "x", "seed": 1, "max_new_tokens": 2})
    hdr = {"Content-Type": "application/json"}

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            for junk in ("banana", "yes", "2"):
                r = await client.post(
                    f"/v1/models/tg:generate?stream={junk}",
                    data=body, headers=hdr)
                assert r.status == 400, (junk, await r.text())
                assert "stream" in (await r.json())["error"]
            # stream=false / 0 serve plain unary JSON.
            r = await client.post("/v1/models/tg:generate?stream=false",
                                  data=body, headers=hdr)
            assert r.status == 200
            assert "X-Tpuserve-Stream" not in r.headers
            assert (await r.json())["n_tokens"] == 2
        finally:
            await client.close()

    # The router relays through this exact validator (single source of
    # truth for the flag's grammar).
    from tpuserve.workerproc import router as router_mod
    assert router_mod._requested_stream is _requested_stream

    run(go())


def test_injected_stream_disconnect_is_a_torn_stream():
    """The "stream_disconnect" fault kind tears a STARTED stream's
    transport with no terminal — the torn shape clients must error on
    (and the drill proves the router converts into an error terminal).
    "stream_stall" is the sibling kind (wedged writer; the router's idle
    timeout owns it) — both are registered FAULT_KINDS."""
    assert "stream_stall" in FAULT_KINDS
    assert "stream_disconnect" in FAULT_KINDS

    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.faults import FaultInjector
    from tpuserve.server import make_app

    state = _gen_server()
    state.injector = FaultInjector.single("stream_disconnect")
    body = json.dumps({"prompt": "torn", "seed": 2, "max_new_tokens": 8})
    hdr = {"Content-Type": "application/json"}

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            r = await client.post("/v1/models/tg:generate?stream=true",
                                  data=body, headers=hdr)
            assert r.status == 200  # the stream STARTED (latch committed)
            try:
                raw = await r.read()
            except Exception:
                raw = b""  # the tear can surface as a transport error
            events = list(SseParser().feed(raw))
            assert not any(e in ("done", "error") for e, _ in events), \
                f"torn stream must carry NO terminal: {events}"
            # The abandon hook frees the slot engine-side.
            deadline = asyncio.get_running_loop().time() + 30.0
            eng = state.engines["tg"]
            while eng.arena.n_active:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            assert eng.arena.n_free == eng.slots
        finally:
            await client.close()

    run(go())


# ---------------------------------------------------------------------------
# Termination-reason vocabulary (TPS404 contract)
# ---------------------------------------------------------------------------

def test_engine_error_terminates_stream_with_reason(tg_rt):
    """A step failure poisons the in-flight set (_fail_active): every
    active stream gets the "engine_error" terminal and the reason is
    counted under gen_stream_terminated_total — the label
    docs/REFERENCE.md documents for engine-side faults."""
    from tpuserve.faults import FaultInjector

    model, _ = tg_rt
    eng, m = make_engine(tg_rt)
    eng.injector = FaultInjector.single("batch_error")

    async def go():
        await eng.start()
        try:
            fut, stream = eng.submit_stream(
                prompt_item(model, "doomed", seed=7, max_new=8))
            units = await drain_stream(stream)
            terminal = units[-1]
            assert terminal["type"] == "error"
            assert terminal["error"] == "engine_error"
            with pytest.raises(Exception):
                await fut
        finally:
            await eng.stop()
        assert m.counter("gen_stream_terminated_total{model=tg,"
                         "reason=engine_error}").value >= 1

    run(go())


def test_engine_termination_vocabulary_is_closed(tg_rt):
    """_count_termination refuses off-vocabulary reasons: a label an
    operator can see on a dashboard must be one docs/REFERENCE.md
    explains and a test exercises (TPS404) — ad-hoc strings would
    fragment the metric and dodge both."""
    from tpuserve.obs import GEN_STREAM_REASONS

    eng, _ = make_engine(tg_rt)
    for reason in GEN_STREAM_REASONS:
        eng._count_termination(reason)  # every documented reason ticks
    with pytest.raises(ValueError, match="unknown stream-termination"):
        eng._count_termination("made_up_reason")
