"""Config loading, overrides, validation (C9)."""

import pytest

from tpuserve.config import ModelConfig, ServerConfig, default_config, load_config


def test_default_config():
    cfg = default_config()
    assert cfg.port == 8000
    assert cfg.models[0].family == "resnet50"


def test_load_toml(tmp_path):
    p = tmp_path / "serve.toml"
    p.write_text(
        """
port = 9001
decode_threads = 4

[[model]]
name = "rn"
family = "resnet50"
batch_buckets = [1, 8]
deadline_ms = 2.5

[[model]]
name = "bert"
family = "bert"
seq_buckets = [64, 128]
"""
    )
    cfg = load_config(str(p))
    assert cfg.port == 9001
    assert cfg.decode_threads == 4
    assert len(cfg.models) == 2
    assert cfg.model("rn").batch_buckets == [1, 8]
    assert cfg.model("rn").deadline_ms == 2.5
    assert cfg.model("bert").seq_buckets == [64, 128]


def test_overrides(tmp_path):
    p = tmp_path / "serve.toml"
    p.write_text('port = 9001\n[[model]]\nname = "rn"\nfamily = "resnet50"\n')
    cfg = load_config(str(p), overrides=["port=7000", "model.rn.deadline_ms=1.5",
                                         "model.rn.batch_buckets=[2, 4]"])
    assert cfg.port == 7000
    assert cfg.model("rn").deadline_ms == 1.5
    assert cfg.model("rn").batch_buckets == [2, 4]


def test_options_dict_override(tmp_path):
    p = tmp_path / "serve.toml"
    p.write_text('[[model]]\nname = "sd"\nfamily = "sd15"\n')
    cfg = load_config(str(p), overrides=["model.sd.options.num_steps=4"])
    assert cfg.model("sd").options["num_steps"] == 4


def test_pipeline_block(tmp_path):
    p = tmp_path / "pipe.toml"
    p.write_text(
        """
[pipeline]
h2d_workers = 4
depth = 3
arena_slots = 8

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    assert cfg.pipeline.h2d_workers == 4
    assert cfg.pipeline.depth == 3
    assert cfg.pipeline.arena_slots == 8
    assert cfg.pipeline.assemble_workers == 2  # default preserved


def test_pipeline_block_validation():
    from tpuserve.config import PipelineConfig

    with pytest.raises(ValueError, match="fetch_workers"):
        PipelineConfig(fetch_workers=0)
    with pytest.raises(ValueError, match=">= 0"):
        PipelineConfig(depth=-1)


def test_unknown_key_rejected(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text("bogus_key = 1\n")
    with pytest.raises(ValueError, match="unknown"):
        load_config(str(p))


def test_unknown_override_field():
    cfg = ServerConfig(models=[ModelConfig(name="m")])
    with pytest.raises(ValueError, match="unknown config field"):
        load_config_overrides(cfg, "model.m.nope=1")


def load_config_overrides(cfg, ov):
    from tpuserve.config import _apply_override

    _apply_override(cfg, ov)


def test_model_lookup_missing():
    cfg = ServerConfig()
    with pytest.raises(KeyError):
        cfg.model("nope")


def test_import_model_cli_parses_opts(monkeypatch):
    """--opt key=value reaches convert_cli as TOML-typed model options."""
    from tpuserve import cli, savedmodel

    captured = {}
    monkeypatch.setattr(
        savedmodel, "convert_cli",
        lambda sm, fam, out, options=None, quantize=None: captured.update(
            {"sm": sm, "fam": fam, "out": out, "quantize": quantize,
             **(options or {})}))
    rc = cli.main(["import-model", "--saved-model", "x", "--family", "bert",
                   "--out", "y", "--opt", "layers=2",
                   "--opt", "vocab_file=v.txt"])
    assert rc == 0
    assert captured == {"sm": "x", "fam": "bert", "out": "y", "quantize": None,
                        "layers": 2, "vocab_file": "v.txt"}


def test_import_model_cli_rejects_reserved_opts():
    from tpuserve import savedmodel

    with pytest.raises(ValueError, match="weights"):
        savedmodel.convert_cli("sm", "toy", "out", {"weights": "/elsewhere"})


def test_example_serve_all_toml_parses_and_builds():
    """The shipped example config parses, covers all five families, and
    every model in it constructs (no compile — just the family builds)."""
    import os

    from tpuserve.models import build

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "serve_all.toml")
    cfg = load_config(path)
    assert {m.family for m in cfg.models} == {
        "resnet50", "mobilenetv3", "bert", "efficientdet", "sd15"}
    for m in cfg.models:
        build(m)


def test_example_bert_modes_toml_parses_and_builds():
    """The r5 modes example (int8c + pipeline serving) parses and both
    models construct with their modes wired."""
    import os

    from tpuserve.models import build

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "bert_modes.toml")
    cfg = load_config(path)
    by_name = {m.name: m for m in cfg.models}
    assert by_name["bert-i8c"].quantize == "int8c"
    assert by_name["bert-pp"].parallelism == "pipeline"
    assert by_name["bert-pp"].pp == 4
    for m in cfg.models:
        model = build(m)
        if m.name == "bert-i8c":
            assert model.int8c_native_kernel_paths()
        else:
            assert model.pipeline_capable


def test_warmup_and_describe_cli(tmp_path, capsys):
    """C10: `warmup` builds+compiles from a TOML config and prints the
    runtime inventory; `describe` prints the device/mesh view."""
    import json

    from tpuserve import cli

    toml = tmp_path / "w.toml"
    toml.write_text(
        'port = 18999\n'
        '[[model]]\n'
        'name = "toy"\n'
        'family = "toy"\n'
        'batch_buckets = [1, 2]\n'
        'dtype = "float32"\n'
        'num_classes = 10\n'
        'parallelism = "single"\n'
    )
    assert cli.main(["warmup", "--config", str(toml)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["toy"]["buckets"] == [[1], [2]]
    assert out["toy"]["quantize"] is None

    assert cli.main(["describe"]) == 0
    desc = json.loads(capsys.readouterr().out)
    assert desc["platform"] == "cpu" and len(desc["devices"]) == 8


def test_cache_and_adaptive_blocks(tmp_path):
    p = tmp_path / "demand.toml"
    p.write_text(
        """
[cache]
enabled = true
capacity = 128
ttl_s = 30.0
coalesce = false

[adaptive]
enabled = false
min_target = 2
decrease = 0.25

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    assert cfg.cache.enabled is True
    assert cfg.cache.capacity == 128
    assert cfg.cache.ttl_s == 30.0
    assert cfg.cache.coalesce is False
    assert cfg.cache.max_body_bytes == 1048576  # default preserved
    assert cfg.adaptive.enabled is False
    assert cfg.adaptive.min_target == 2
    assert cfg.adaptive.decrease == 0.25
    assert cfg.adaptive.increase == 1.0  # default preserved


def test_cache_and_adaptive_defaults_and_validation():
    from tpuserve.config import AdaptiveConfig, CacheConfig

    cfg = ServerConfig(models=[ModelConfig(name="m")])
    assert cfg.cache.enabled is False  # only deterministic models may opt in
    assert cfg.adaptive.enabled is True
    with pytest.raises(ValueError, match="capacity"):
        CacheConfig(capacity=0)
    with pytest.raises(ValueError, match=">= 0"):
        CacheConfig(ttl_s=-1.0)
    with pytest.raises(ValueError, match="min_target"):
        AdaptiveConfig(min_target=0)
    with pytest.raises(ValueError, match="decrease"):
        AdaptiveConfig(decrease=1.5)
    with pytest.raises(ValueError, match="ewma_alpha"):
        AdaptiveConfig(ewma_alpha=0.0)


def test_parallel_block(tmp_path):
    """[parallel] (ISSUE 7): the multi-chip serving plan parses from TOML
    and from dot-path overrides; invalid modes reject at construction."""
    from tpuserve.config import ParallelConfig

    p = tmp_path / "serve.toml"
    p.write_text(
        """
[parallel]
mode = "replica"
n_chips = 4

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    assert cfg.parallel.mode == "replica"
    assert cfg.parallel.n_chips == 4
    assert cfg.parallel.data == 0

    cfg = load_config(str(p), overrides=["parallel.mode=sharded",
                                         "parallel.data=8"])
    assert cfg.parallel.mode == "sharded" and cfg.parallel.data == 8

    # Defaults: per-model parallelism rules, all chips.
    assert ServerConfig().parallel.mode == ""
    with pytest.raises(ValueError, match="parallel.mode"):
        ParallelConfig(mode="pipeline")
    with pytest.raises(ValueError, match="n_chips"):
        ParallelConfig(data=-1)


def test_router_and_worker_blocks(tmp_path):
    """[router]/[worker] (ISSUE 8): the process-split plan parses from TOML
    and dot-path overrides; invalid knobs reject at construction."""
    from tpuserve.config import RouterConfig, WorkerConfig

    p = tmp_path / "serve.toml"
    p.write_text(
        """
[router]
enabled = true
workers = 4
retry_max = 1
hedge_ms = 25.0
respawn_initial_s = 0.25

[worker]
port_base = 9100

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    assert cfg.router.enabled and cfg.router.workers == 4
    assert cfg.router.retry_max == 1 and cfg.router.hedge_ms == 25.0
    assert cfg.router.respawn_initial_s == 0.25
    assert cfg.worker.port_base == 9100
    assert cfg.worker.host == "127.0.0.1"

    cfg = load_config(str(p), overrides=["router.workers=8",
                                         "worker.drain_timeout_s=2.5"])
    assert cfg.router.workers == 8
    assert cfg.worker.drain_timeout_s == 2.5

    # Defaults: single-process serving, split off.
    assert ServerConfig().router.enabled is False
    with pytest.raises(ValueError, match="router.workers"):
        RouterConfig(workers=0)
    with pytest.raises(ValueError, match="retry_max"):
        RouterConfig(retry_max=-1)
    with pytest.raises(ValueError, match="respawn"):
        RouterConfig(respawn_multiplier=0.5)
    with pytest.raises(ValueError, match="unhealthy_after"):
        RouterConfig(unhealthy_after=0)
    with pytest.raises(ValueError, match="port_base"):
        WorkerConfig(port_base=-1)


def test_router_hosts_and_routers_knobs(tmp_path):
    """[router] hosts/routers (ISSUE 13): the host failure-domain and
    horizontal-router topology parses, defaults stay flat/single, and
    invalid values reject at construction."""
    from tpuserve.config import RouterConfig

    p = tmp_path / "serve.toml"
    p.write_text(
        """
[router]
enabled = true
hosts = 2
workers = 2
routers = 3
host_breaker_threshold = 5
host_breaker_cooldown_s = 0.5
peer_sync_interval_s = 0.25
peer_port = 9300

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    assert cfg.router.hosts == 2 and cfg.router.workers == 2
    assert cfg.router.routers == 3
    assert cfg.router.host_breaker_threshold == 5
    assert cfg.router.host_breaker_cooldown_s == 0.5
    assert cfg.router.peer_sync_interval_s == 0.25
    assert cfg.router.peer_port == 9300

    cfg = load_config(str(p), overrides=["router.hosts=4",
                                         "router.routers=1"])
    assert cfg.router.hosts == 4 and cfg.router.routers == 1

    # Defaults: no host layer, one router — the PR-8 flat topology.
    assert ServerConfig().router.hosts == 0
    assert ServerConfig().router.routers == 1
    with pytest.raises(ValueError, match="hosts"):
        RouterConfig(hosts=-1)
    with pytest.raises(ValueError, match="routers"):
        RouterConfig(routers=0)
    with pytest.raises(ValueError, match="host_breaker"):
        RouterConfig(host_breaker_cooldown_s=0.0)
    with pytest.raises(ValueError, match="peer_sync_interval_s"):
        RouterConfig(peer_sync_interval_s=0.0)
    with pytest.raises(ValueError, match="peer_port"):
        RouterConfig(peer_port=-1)


def test_trace_block(tmp_path):
    p = tmp_path / "trace.toml"
    p.write_text(
        """
[trace]
slow_n = 4
error_capacity = 32
always_record_errors = false
exemplars = false

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    assert cfg.trace.slow_n == 4
    assert cfg.trace.error_capacity == 32
    assert cfg.trace.always_record_errors is False
    assert cfg.trace.exemplars is False
    # Defaults + dot-path override.
    cfg2 = load_config(None, overrides=["trace.slow_n=9"])
    assert cfg2.trace.slow_n == 9
    assert cfg2.trace.exemplars is True


def test_trace_block_validation():
    from tpuserve.config import TraceConfig

    with pytest.raises(ValueError, match="slow_n"):
        TraceConfig(slow_n=-1)
    with pytest.raises(ValueError, match="error_capacity"):
        TraceConfig(error_capacity=-1)


def test_events_block(tmp_path):
    p = tmp_path / "events.toml"
    p.write_text(
        """
[events]
capacity = 128
jsonl_path = "/tmp/ev.jsonl"
bridge_level = "WARNING"
dir = "/tmp/bb"
snapshot_interval_s = 0.5
stderr_tail_bytes = 1024
audit_capacity = 32
postmortem_capacity = 8

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    assert cfg.events.enabled is True
    assert cfg.events.capacity == 128
    assert cfg.events.jsonl_path == "/tmp/ev.jsonl"
    assert cfg.events.bridge_level == "WARNING"
    assert cfg.events.dir == "/tmp/bb"
    assert cfg.events.snapshot_interval_s == 0.5
    assert cfg.events.stderr_tail_bytes == 1024
    assert cfg.events.audit_capacity == 32
    assert cfg.events.postmortem_capacity == 8
    # Defaults + dot-path override.
    cfg2 = load_config(None, overrides=["events.enabled=false"])
    assert cfg2.events.enabled is False
    assert cfg2.events.capacity == 4096
    assert cfg2.events.stderr_path == "" and cfg2.events.snapshot_path == ""


def test_tenants_block(tmp_path):
    p = tmp_path / "tenants.toml"
    p.write_text(
        """
[tenants]
enabled = true
window_s = 30.0
allow_anonymous = "public"
share_slack = 1.5
slo_latency_ms = 250.0
slo_availability = 0.995
slo_burn_alert = 6.0

[[tenants.tenant]]
name = "acme"
api_key = "acme-key"
weight = 3.0
quota_device_s = 10.0
rate_per_s = 20.0
burst = 40.0

[[tenants.tenant]]
name = "tiny"
api_key = "tiny-key"

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    t = cfg.tenants
    assert t.enabled is True
    assert t.window_s == 30.0
    assert t.allow_anonymous == "public"
    assert t.share_slack == 1.5
    assert t.slo_latency_ms == 250.0
    assert t.slo_availability == 0.995
    assert t.slo_burn_alert == 6.0
    assert [x.name for x in t.tenants] == ["acme", "tiny"]
    acme = t.tenants[0]
    assert acme.api_key == "acme-key"
    assert acme.weight == 3.0
    assert acme.quota_device_s == 10.0
    assert acme.rate_per_s == 20.0
    assert acme.burst == 40.0
    # The second entry rides on defaults: weight 1, no envelope.
    assert t.tenants[1].weight == 1.0
    assert t.tenants[1].quota_device_s == 0.0
    # Defaults + dot-path override.
    cfg2 = load_config(None, overrides=["tenants.enabled=true"])
    assert cfg2.tenants.enabled is True
    assert cfg2.tenants.window_s == 60.0
    assert cfg2.tenants.tenants == []


def test_tenants_block_validation(tmp_path):
    from tpuserve.config import TenantConfig, TenantsConfig

    with pytest.raises(ValueError, match="window_s"):
        TenantsConfig(window_s=0.0)
    with pytest.raises(ValueError, match="share_slack"):
        TenantsConfig(share_slack=-1.0)
    with pytest.raises(ValueError, match="slo_latency_ms"):
        TenantsConfig(slo_latency_ms=-1.0)
    with pytest.raises(ValueError, match="slo_availability"):
        TenantsConfig(slo_availability=1.0)
    with pytest.raises(ValueError, match="slo_burn_alert"):
        TenantsConfig(slo_burn_alert=0.0)
    with pytest.raises(ValueError, match="name"):
        TenantConfig(name="", api_key="k")
    with pytest.raises(ValueError, match="api_key"):
        TenantConfig(name="t", api_key="")
    with pytest.raises(ValueError, match="weight"):
        TenantConfig(name="t", api_key="k", weight=0.0)
    with pytest.raises(ValueError, match="quota_device_s"):
        TenantConfig(name="t", api_key="k", quota_device_s=-1.0)
    # Duplicate names/keys are rejected when the TOML list is assembled.
    p = tmp_path / "dup.toml"
    p.write_text(
        """
[tenants]
enabled = true

[[tenants.tenant]]
name = "a"
api_key = "k1"

[[tenants.tenant]]
name = "a"
api_key = "k2"

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    with pytest.raises(ValueError, match="unique"):
        load_config(str(p))


def test_autopilot_block(tmp_path):
    p = tmp_path / "autopilot.toml"
    p.write_text(
        """
[autopilot]
enabled = true
interval_s = 0.25
hysteresis_ticks = 2
cooldown_s = 3.0
max_actions_per_window = 4
window_s = 30.0
follow_up_s = 5.0
rollback_tolerance = 0.25
pressure_high = 1.5
pressure_low = 0.1
clear_high_s = 8.0
min_slots = 2
burn_shed = false
scale = true
paging = true
max_warm = 2
history = 64

[[model]]
name = "rn"
family = "resnet50"
"""
    )
    cfg = load_config(str(p))
    a = cfg.autopilot
    assert a.enabled is True
    assert a.interval_s == 0.25
    assert a.hysteresis_ticks == 2
    assert a.cooldown_s == 3.0
    assert a.max_actions_per_window == 4
    assert a.window_s == 30.0
    assert a.follow_up_s == 5.0
    assert a.rollback_tolerance == 0.25
    assert a.pressure_high == 1.5
    assert a.pressure_low == 0.1
    assert a.clear_high_s == 8.0
    assert a.min_slots == 2
    assert a.burn_shed is False
    assert a.scale is True
    assert a.paging is True
    assert a.max_warm == 2
    assert a.history == 64
    # Defaults + dot-path override.
    cfg2 = load_config(None, overrides=["autopilot.enabled=true"])
    assert cfg2.autopilot.enabled is True
    assert cfg2.autopilot.interval_s == 0.5
    assert cfg2.autopilot.hysteresis_ticks == 3
    assert cfg2.autopilot.paging is False


def test_autopilot_block_validation():
    from tpuserve.config import AutopilotConfig

    with pytest.raises(ValueError, match="interval_s"):
        AutopilotConfig(interval_s=0.0)
    with pytest.raises(ValueError, match="hysteresis_ticks"):
        AutopilotConfig(hysteresis_ticks=0)
    with pytest.raises(ValueError, match="max_actions_per_window"):
        AutopilotConfig(max_actions_per_window=0)
    with pytest.raises(ValueError, match="cooldown_s"):
        AutopilotConfig(cooldown_s=-1.0)
    with pytest.raises(ValueError, match="follow_up_s"):
        AutopilotConfig(follow_up_s=-1.0)
    with pytest.raises(ValueError, match="pressure_low"):
        AutopilotConfig(pressure_low=2.0, pressure_high=1.0)
    with pytest.raises(ValueError, match="min_slots"):
        AutopilotConfig(min_slots=0)
