"""Paged KV cache + chunked prefill (ISSUE 18): page-ledger safety,
paged==dense token parity, zero recompiles across page/slot churn and
reload, chunked-prefill determinism and non-starvation, page-pressure
admission over HTTP, and the fleet predictor's kv term.
docs/PERFORMANCE.md "Paged KV & chunked prefill"."""

import asyncio
import json

import pytest

from tpuserve.config import GenserveConfig, ModelConfig, ServerConfig
from tpuserve.genserve import (GenEngine, KVPressure, PageCorrupted,
                               PageLedger)
from tpuserve.models import build
from tpuserve.obs import Metrics
from tpuserve.runtime import build_runtime

TG_OPTS = dict(layers=1, d_model=32, heads=2, d_ff=64, vocab_size=512,
               prompt_len=16, max_new_tokens=64)


def tg_cfg(**over) -> ModelConfig:
    base = dict(name="tg", family="textgen", batch_buckets=[1, 2, 4],
                dtype="float32", parallelism="single", max_queue=64,
                request_timeout_ms=60_000.0, options=dict(TG_OPTS))
    base.update(over)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def dense_rt():
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    GenEngine(model, rt, Metrics(), GenserveConfig(slots=4)).compile()
    return model, rt


@pytest.fixture(scope="module")
def paged_rt():
    """Same model config as dense_rt (identical deterministic params), own
    runtime because the paged geometry registers different programs."""
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    GenEngine(model, rt, Metrics(), GenserveConfig(
        slots=4, kv_paging=True, kv_page_tokens=8)).compile()
    return model, rt


@pytest.fixture(scope="module")
def chunked_rt():
    """prefill_chunk=4 is a different geometry again (its prefill program
    closes over the chunk width)."""
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    GenEngine(model, rt, Metrics(), GenserveConfig(
        slots=4, kv_paging=True, kv_page_tokens=8, prefill_chunk=4)).compile()
    return model, rt


def make_engine(fix, metrics=None, slots=4, **gc_over):
    model, rt = fix
    m = metrics or Metrics()
    eng = GenEngine(model, rt, m, GenserveConfig(slots=slots, **gc_over))
    eng.compile()  # reuses the runtime's registered programs
    return eng, m


def paged_over(**over):
    base = dict(kv_paging=True, kv_page_tokens=8)
    base.update(over)
    return base


def prompt_item(model, prompt="hello world", seed=0, max_new=8, temp=0.0):
    body = {"prompt": prompt, "seed": seed, "max_new_tokens": max_new}
    if temp:
        body["temperature"] = temp
    return model.host_decode(json.dumps(body).encode(), "application/json")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# PageLedger: never double-hands
# ---------------------------------------------------------------------------

def test_page_ledger_never_double_hands():
    led = PageLedger(4, 8)  # sentinel + 3 usable
    assert led.usable == 3 and led.n_free == 3
    a = led.acquire(0, 2)
    assert a == [1, 2] and PageLedger.SENTINEL not in a
    b = led.acquire(1, 1)
    assert b == [3] and led.n_free == 0
    with pytest.raises(IndexError):
        led.acquire(2, 1)  # pool exhausted
    with pytest.raises(PageCorrupted):
        led.acquire(0, 1)  # slot 0 already holds pages
    assert led.release(0) == [1, 2]
    with pytest.raises(PageCorrupted):
        led.release(0)  # double release
    with pytest.raises(PageCorrupted):
        led.release(7)  # foreign release: slot never held pages
    # A tampered free-list (owned page re-listed) is caught at acquire.
    led._free.append(3)
    with pytest.raises(PageCorrupted):
        led.acquire(5, 1)


def test_page_ledger_release_all_and_stats():
    led = PageLedger(6, 16)
    led.acquire(0, 2)
    led.acquire(1, 3)
    s = led.stats()
    assert s["usable"] == 5 and s["reserved"] == 5 and s["free"] == 0
    assert s["utilization"] == 1.0 and s["acquires_total"] == 5
    assert led.release_all() == 5
    assert led.n_free == led.usable and led.n_reserved == 0
    assert led.utilization() == 0.0
    with pytest.raises(ValueError):
        PageLedger(1, 8)  # no room for the sentinel + one real page
    with pytest.raises(ValueError):
        PageLedger(4, 0)


def test_kv_config_validation(paged_rt):
    with pytest.raises(ValueError, match="kv_pages"):
        GenserveConfig(kv_pages=1)
    with pytest.raises(ValueError, match="kv_page_tokens"):
        GenserveConfig(kv_page_tokens=0)
    with pytest.raises(ValueError, match="prefill_chunk"):
        GenserveConfig(prefill_chunk=-1)
    # A pool that cannot cover even ONE max-context request rejects at
    # engine construction (pps=ceil(80/8)=10, so 11 is the floor).
    model, rt = paged_rt
    with pytest.raises(ValueError, match="cover"):
        GenEngine(model, rt, Metrics(),
                  GenserveConfig(slots=4, **paged_over(kv_pages=5)))


# ---------------------------------------------------------------------------
# Parity: the tentpole acceptance bar
# ---------------------------------------------------------------------------

def test_paged_matches_dense_token_identical(dense_rt, paged_rt):
    """Default (whole-prompt) paged prefill routes through the SAME dense
    init_state math and the paged decode computes the same attention through
    the block table — tokens must be byte-identical, not approximately
    equal, over mixed lengths / seeds / temperatures."""
    d_model, _ = dense_rt
    p_model, _ = paged_rt
    d_eng, _ = make_engine(dense_rt)
    p_eng, _ = make_engine(paged_rt, **paged_over())

    prompts = [
        ("a", 1, 3, 0.0),
        ("the quick brown fox jumps over the lazy dog again and again", 2,
         12, 0.7),
        ("short prompt", 3, 1, 0.0),
        ("one two three four five six seven eight nine ten eleven twelve "
         "thirteen fourteen fifteen sixteen", 4, 8, 0.3),
        ("hello", 5, 20, 1.0),
        ("mid size prompt with a few words", 6, 5, 0.0),
    ]

    async def drive(eng, model):
        await eng.start()
        futs = [eng.submit(prompt_item(model, p, seed=s, max_new=n, temp=t))
                for (p, s, n, t) in prompts]
        res = await asyncio.gather(*futs)
        await eng.stop()
        return [r["tokens"] for r in res]

    dense = run(drive(d_eng, d_model))
    paged = run(drive(p_eng, p_model))
    assert dense == paged, (dense, paged)
    # The ledger balanced after the drain — every page came home.
    assert p_eng.pages.n_free == p_eng.pages.usable
    assert p_eng.pages.n_reserved == 0


def test_paged_zero_recompiles_across_churn_and_reload(paged_rt):
    """Page churn + slot churn + a publish AND a rollback mid-churn with
    runtime_compiles_total delta exactly 0: page indices and block-table
    rows are traced arguments, never baked into the program."""
    model, rt = paged_rt
    eng, _m = make_engine(paged_rt, **paged_over())
    c0 = rt.compiles_total
    assert c0 >= 3  # prefill/step/extract registered

    async def go():
        await eng.start()
        futs = [eng.submit(prompt_item(model, f"p{i} " + "w " * (i % 13),
                                       seed=i, max_new=1 + (i % 9)))
                for i in range(8)]
        rt.publish(rt.stage_params())  # reload mid-churn
        futs += [eng.submit(prompt_item(model, f"q{i}", seed=100 + i,
                                        max_new=2 + (i % 5)))
                 for i in range(8)]
        rt.rollback()
        futs += [eng.submit(prompt_item(model, f"r{i}", seed=200 + i,
                                        max_new=3)) for i in range(4)]
        res = await asyncio.gather(*futs)
        await eng.stop()
        return res

    res = run(go())
    assert len(res) == 20 and all(r["n_tokens"] >= 1 for r in res)
    assert rt.compiles_total == c0, (rt.compiles_total, c0)
    # Slot AND page accounting survived the churn exactly.
    assert eng.arena.n_active == 0 and eng.arena.n_free == eng.slots
    assert eng.pages.n_reserved == 0
    assert eng.pages.n_free == eng.pages.usable


# ---------------------------------------------------------------------------
# Chunked prefill
# ---------------------------------------------------------------------------

LONG16 = ("one two three four five six seven eight nine ten eleven twelve "
          "thirteen fourteen fifteen sixteen")


def test_chunked_prefill_deterministic_under_load(chunked_rt):
    """A max-length prompt prefilled in 4-token chunks emits the same
    tokens alone and amid decode load — chunk boundaries are fixed by the
    prompt, not by what else occupies the batch."""
    model, _ = chunked_rt
    e_alone, _ = make_engine(chunked_rt, **paged_over(prefill_chunk=4))
    e_load, _ = make_engine(chunked_rt, **paged_over(prefill_chunk=4))

    async def alone():
        await e_alone.start()
        r = await e_alone.submit(
            prompt_item(model, LONG16, seed=9, max_new=8, temp=0.5))
        await e_alone.stop()
        return r["tokens"]

    async def amid_load():
        await e_load.start()
        futs = [e_load.submit(prompt_item(model, "short one", seed=i + 1,
                                          max_new=3)) for i in range(3)]
        long_f = e_load.submit(
            prompt_item(model, LONG16, seed=9, max_new=8, temp=0.5))
        futs += [e_load.submit(prompt_item(model, "another short",
                                           seed=i + 10, max_new=4))
                 for i in range(3)]
        out = await asyncio.gather(long_f, *futs)
        await e_load.stop()
        return out[0]["tokens"]

    assert run(alone()) == run(amid_load())
    assert e_alone.pages.n_reserved == 0 and e_load.pages.n_reserved == 0


def test_chunked_prefill_never_starves_decode(chunked_rt):
    """THE interleaving property: short decodes admitted alongside a
    max-length prompt all complete while the long one is still working —
    prefill advances one chunk per engine iteration instead of stalling
    the step loop for the whole prompt."""
    model, _ = chunked_rt
    eng, m = make_engine(chunked_rt, **paged_over(prefill_chunk=4))

    async def go():
        await eng.start()
        order = []
        # 16-token prompt -> 4 prefill chunks + 8 decode steps.
        long_f = eng.submit(prompt_item(model, LONG16, seed=1, max_new=8))
        long_f.add_done_callback(lambda f: order.append("long"))
        shorts = []
        for i in range(3):
            f = eng.submit(prompt_item(model, "hi", seed=10 + i, max_new=2))
            f.add_done_callback(lambda f, i=i: order.append(f"s{i}"))
            shorts.append(f)
        await asyncio.gather(long_f, *shorts)
        await eng.stop()
        return order

    order = run(go())
    assert order[-1] == "long", order  # every short finished first
    assert set(order[:-1]) == {"s0", "s1", "s2"}
    # 4 chunks for the long prompt + 1 whole-prompt chunk per short.
    assert m.counter(
        "gen_prefill_chunks_total{model=tg}").value == pytest.approx(7)


# ---------------------------------------------------------------------------
# Page-pressure admission
# ---------------------------------------------------------------------------

def test_kv_pressure_sheds_beyond_backlog_bound():
    """Projected demand beyond one pool turnover of backlog sheds with
    KVPressure (a QueueFull subclass: existing handling still works), and
    the kv_pressure shed reason is counted."""
    # Own runtime: the pool size is part of the compiled state shape.
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    m = Metrics()
    eng = GenEngine(model, rt, m, GenserveConfig(
        slots=4, **paged_over(kv_pages=11)))  # 10 usable, bound 20
    eng.compile()

    async def go():
        await eng.start()
        # Each needs ceil((4 + 60) / 8) = 8 pages.
        item = lambda s: prompt_item(model, "hold the pool please",
                                     seed=s, max_new=60)
        f1, f2 = eng.submit(item(1)), eng.submit(item(2))
        with pytest.raises(KVPressure):
            eng.submit(item(3))  # projected 24 > 20
        await asyncio.gather(f1, f2)
        await eng.stop()

    run(go())
    assert m.counter(
        "sched_sheds_total{model=tg,reason=kv_pressure}").value == 1
    assert eng.pages.n_reserved == 0


def test_kv_clear_s_and_fleet_predictor():
    """kv_clear_s: None while the pool is comfortable, a positive
    clear-time once pressure + evidence exist; the fleet predictor folds
    it in even with an empty queue."""
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    eng = GenEngine(model, rt, Metrics(), GenserveConfig(
        slots=4, **paged_over()))
    eng.compile()
    assert eng.kv_clear_s() is None  # comfortable pool, no evidence
    eng._ewma_step_ms = 10.0
    eng._ewma_iters = 5.0
    eng._ewma_pages = float(eng.pages.usable + 1)  # n_free < typical need
    assert eng.kv_clear_s() == pytest.approx(0.05)

    from tpuserve.config import SchedulerConfig
    from tpuserve.scheduler.fleet import FleetScheduler

    class StubPaged:
        device_time_cb = None

        def estimate_clear_s(self):
            return None  # empty queue

        def kv_clear_s(self):
            return 1.5

        def predicted_service_s(self, n_items=1):
            return 0.5

    sched = FleetScheduler(SchedulerConfig(enabled=True), Metrics())
    sched.register("m", StubPaged(), tg_cfg(name="m"))
    assert sched.predict_completion_s("m") == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# HTTP front door: 503 + Retry-After + observability
# ---------------------------------------------------------------------------

def test_http_kv_pressure_503_and_stats():
    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(
        decode_threads=2,
        genserve=GenserveConfig(enabled=True, slots=4, kv_paging=True,
                                kv_page_tokens=8, kv_pages=11),
        models=[tg_cfg()])
    state = ServerState(cfg)
    state.build()

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            # Warm one request to completion: establishes the step/iters
            # EWMAs that price the Retry-After hint.
            warm = await client.post(
                "/v1/models/tg:generate",
                data=json.dumps({"prompt": "warm", "seed": 1,
                                 "max_new_tokens": 2}),
                headers={"Content-Type": "application/json"})
            assert warm.status == 200, await warm.text()
            # Saturate the pool (10 usable, backlog bound 20) with two
            # 8-page reservations queued engine-side, then the third over
            # HTTP sheds BEFORE enqueue.
            eng = state.batchers["tg"]
            body = lambda s: json.dumps({"prompt": "hold the pool please",
                                         "seed": s, "max_new_tokens": 60})
            item = lambda s: eng.model.host_decode(body(s).encode(),
                                                   "application/json")
            f1, f2 = eng.submit(item(1)), eng.submit(item(2))
            shed = await client.post(
                "/v1/models/tg:generate", data=body(3),
                headers={"Content-Type": "application/json"})
            assert shed.status == 503, await shed.text()
            payload = await shed.json()
            assert payload["reason"] == "kv_pressure"
            assert int(shed.headers["Retry-After"]) >= 1
            # /stats carries the kv block; /metrics the page gauges.
            stats = await (await client.get("/stats")).json()
            kv = stats["genserve"]["tg"]["kv"]
            assert kv["pages"] == 11 and kv["page_tokens"] == 8
            assert kv["kv_bytes"] > 0
            metrics = await (await client.get("/metrics")).text()
            assert 'gen_kv_pages_total{model="tg"}' in metrics
            assert 'gen_kv_pages_free{model="tg"}' in metrics
            assert 'gen_kv_page_utilization{model="tg"}' in metrics
            assert ('sched_sheds_total{model="tg",reason="kv_pressure"}'
                    in metrics)
            await asyncio.gather(f1, f2)
        finally:
            await client.close()

    run(go())
