"""Randomized-weight torch-vs-JAX parity for the SD 1.5 import path
(SURVEY.md §4-4 applied to C6's torch reader; VERDICT r3 next 2).

Mirrors tests/test_tf_parity.py's method: build a REAL torch model in the
published artifact's layout, randomize its weights, export its state_dict,
import through ``tpuserve.models.sd15_import``, and assert the JAX forward
reproduces the torch forward. The text tower runs against transformers'
actual ``CLIPTextModel`` (fully independent implementation); the UNet and
VAE run against minimal torch references written here that follow the
LDM/CompVis module layout (same state_dict keys real checkpoints carry:
``input_blocks.{k}.0.in_layers.0``, ``decoder.up.{i}.block.{j}``, ...).

This is the test that catches every translation hazard in the mapper:
conv/linear transposes, MHA head reshapes, the GEGLU half-swap, missing
q/k/v biases, per-site GroupNorm epsilons, and the up/down block numbering.
"""

import math

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpuserve.models import sd15_import  # noqa: E402

pytestmark = pytest.mark.slow

# Tiny-but-structurally-complete SD config shared by all parity tests.
CH, MULTS, NRES, ATTN, HEADS = 8, (1, 2), 1, (0, 1), 2
TXT_D, TXT_LAYERS, TXT_HEADS, VOCAB = 16, 2, 2, 99
VAE_CH, VAE_MULTS = 8, (1, 2)

TOL = dict(rtol=2e-3, atol=2e-4)


def seed_all():
    torch.manual_seed(0)
    np.random.seed(0)


def randomize(m: tnn.Module) -> tnn.Module:
    """Non-degenerate random weights everywhere (incl. norm scales/biases)."""
    with torch.no_grad():
        for p in m.parameters():
            p.copy_(torch.randn_like(p) * 0.2)
    return m.eval()


def sd_numpy(m: tnn.Module, prefix: str = "") -> dict:
    return {prefix + k: v.numpy() for k, v in m.state_dict().items()}


# -- torch reference modules (LDM layout) -------------------------------------

def gn(ch: int, eps: float) -> tnn.GroupNorm:
    return tnn.GroupNorm(math.gcd(32, ch), ch, eps=eps)


class TRes(tnn.Module):
    """LDM openaimodel.ResBlock: in_layers/emb_layers/out_layers naming."""

    def __init__(self, in_ch, out_ch, temb_ch):
        super().__init__()
        self.in_layers = tnn.Sequential(
            gn(in_ch, 1e-5), tnn.SiLU(), tnn.Conv2d(in_ch, out_ch, 3, padding=1))
        self.emb_layers = tnn.Sequential(tnn.SiLU(), tnn.Linear(temb_ch, out_ch))
        self.out_layers = tnn.Sequential(
            gn(out_ch, 1e-5), tnn.SiLU(), tnn.Identity(),
            tnn.Conv2d(out_ch, out_ch, 3, padding=1))
        self.skip_connection = (tnn.Conv2d(in_ch, out_ch, 1)
                                if in_ch != out_ch else tnn.Identity())

    def forward(self, x, emb):
        h = self.in_layers(x)
        h = h + self.emb_layers(emb)[:, :, None, None]
        return self.skip_connection(x) + self.out_layers(h)


class TAttn(tnn.Module):
    """LDM CrossAttention: to_q/to_k/to_v (no bias) + to_out.0."""

    def __init__(self, d, ctx_d, heads):
        super().__init__()
        self.heads = heads
        self.to_q = tnn.Linear(d, d, bias=False)
        self.to_k = tnn.Linear(ctx_d, d, bias=False)
        self.to_v = tnn.Linear(ctx_d, d, bias=False)
        self.to_out = tnn.Sequential(tnn.Linear(d, d))

    def forward(self, x, ctx=None):
        ctx = x if ctx is None else ctx
        b, n, d = x.shape
        h, hd = self.heads, d // self.heads
        q = self.to_q(x).view(b, n, h, hd).transpose(1, 2)
        k = self.to_k(ctx).view(b, ctx.shape[1], h, hd).transpose(1, 2)
        v = self.to_v(ctx).view(b, ctx.shape[1], h, hd).transpose(1, 2)
        a = torch.softmax(q @ k.transpose(-1, -2) / math.sqrt(hd), dim=-1)
        return self.to_out((a @ v).transpose(1, 2).reshape(b, n, d))


class TGEGLU(tnn.Module):
    def __init__(self, d, inner):
        super().__init__()
        self.proj = tnn.Linear(d, inner * 2)

    def forward(self, x):
        x, gate = self.proj(x).chunk(2, dim=-1)
        return x * F.gelu(gate)


class TFeedForward(tnn.Module):
    def __init__(self, d):
        super().__init__()
        self.net = tnn.Sequential(TGEGLU(d, 4 * d), tnn.Identity(),
                                  tnn.Linear(4 * d, d))

    def forward(self, x):
        return self.net(x)


class TBasic(tnn.Module):
    def __init__(self, d, ctx_d, heads):
        super().__init__()
        self.norm1 = tnn.LayerNorm(d)
        self.attn1 = TAttn(d, d, heads)
        self.norm2 = tnn.LayerNorm(d)
        self.attn2 = TAttn(d, ctx_d, heads)
        self.norm3 = tnn.LayerNorm(d)
        self.ff = TFeedForward(d)

    def forward(self, x, ctx):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), ctx)
        return x + self.ff(self.norm3(x))


class TSpatial(tnn.Module):
    def __init__(self, ch, ctx_d, heads):
        super().__init__()
        self.norm = gn(ch, 1e-6)
        self.proj_in = tnn.Conv2d(ch, ch, 1)
        self.transformer_blocks = tnn.ModuleList([TBasic(ch, ctx_d, heads)])
        self.proj_out = tnn.Conv2d(ch, ch, 1)

    def forward(self, x, ctx):
        b, c, h, w = x.shape
        x_in = x
        x = self.proj_in(self.norm(x))
        x = x.reshape(b, c, h * w).permute(0, 2, 1)
        x = self.transformer_blocks[0](x, ctx)
        x = x.permute(0, 2, 1).reshape(b, c, h, w)
        return x_in + self.proj_out(x)


class TDown(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.op = tnn.Conv2d(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.op(x)


class TUp(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = tnn.Conv2d(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2, mode="nearest"))


def t_timestep_embedding(t, dim):
    half = dim // 2
    freqs = torch.exp(-math.log(10000.0) * torch.arange(half).float() / half)
    args = t.float()[:, None] * freqs[None, :]
    return torch.cat([torch.cos(args), torch.sin(args)], dim=-1)


class TUNet(tnn.Module):
    """LDM UNetModel skeleton with identical state_dict numbering."""

    def __init__(self, ch=CH, mults=MULTS, num_res=NRES, attn=ATTN,
                 heads=HEADS, ctx_d=TXT_D):
        super().__init__()
        temb = 4 * ch
        self.attn_levels = attn
        self.time_embed = tnn.Sequential(
            tnn.Linear(ch, temb), tnn.SiLU(), tnn.Linear(temb, temb))
        self.input_blocks = tnn.ModuleList(
            [tnn.ModuleList([tnn.Conv2d(4, ch, 3, padding=1)])])
        cur = ch
        for i, m in enumerate(mults):
            for _ in range(num_res):
                entry = [TRes(cur, ch * m, temb)]
                cur = ch * m
                if i in attn:
                    entry.append(TSpatial(cur, ctx_d, heads))
                self.input_blocks.append(tnn.ModuleList(entry))
            if i != len(mults) - 1:
                self.input_blocks.append(tnn.ModuleList([TDown(cur)]))
        self.middle_block = tnn.ModuleList(
            [TRes(cur, cur, temb), TSpatial(cur, ctx_d, heads),
             TRes(cur, cur, temb)])
        # Skip-channel bookkeeping replays the down path.
        skips = [ch]
        c2 = ch
        for i, m in enumerate(mults):
            for _ in range(num_res):
                c2 = ch * m
                skips.append(c2)
            if i != len(mults) - 1:
                skips.append(c2)
        self.output_blocks = tnn.ModuleList()
        for i, m in reversed(list(enumerate(mults))):
            for j in range(num_res + 1):
                entry = [TRes(cur + skips.pop(), ch * m, temb)]
                cur = ch * m
                if i in attn:
                    entry.append(TSpatial(cur, ctx_d, heads))
                if i != 0 and j == num_res:
                    entry.append(TUp(cur))
                self.output_blocks.append(tnn.ModuleList(entry))
        self.out = tnn.Sequential(gn(cur, 1e-5), tnn.SiLU(),
                                  tnn.Conv2d(cur, 4, 3, padding=1))
        self.model_ch = ch

    def _apply_entry(self, entry, h, emb, ctx):
        for mod in entry:
            if isinstance(mod, TRes):
                h = mod(h, emb)
            elif isinstance(mod, TSpatial):
                h = mod(h, ctx)
            else:
                h = mod(h)
        return h

    def forward(self, x, t, ctx):
        emb = self.time_embed(t_timestep_embedding(t, self.model_ch))
        h = self.input_blocks[0][0](x)
        hs = [h]
        for entry in list(self.input_blocks)[1:]:
            h = self._apply_entry(entry, h, emb, ctx)
            hs.append(h)
        h = self._apply_entry(self.middle_block, h, emb, ctx)
        for entry in self.output_blocks:
            h = torch.cat([h, hs.pop()], dim=1)
            h = self._apply_entry(entry, h, emb, ctx)
        return self.out(h)


class TVAERes(tnn.Module):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm1 = gn(in_ch, 1e-6)
        self.conv1 = tnn.Conv2d(in_ch, out_ch, 3, padding=1)
        self.norm2 = gn(out_ch, 1e-6)
        self.conv2 = tnn.Conv2d(out_ch, out_ch, 3, padding=1)
        if in_ch != out_ch:
            self.nin_shortcut = tnn.Conv2d(in_ch, out_ch, 1)

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "nin_shortcut"):
            x = self.nin_shortcut(x)
        return x + h


class TVAEAttn(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.norm = gn(ch, 1e-6)
        self.q = tnn.Conv2d(ch, ch, 1)
        self.k = tnn.Conv2d(ch, ch, 1)
        self.v = tnn.Conv2d(ch, ch, 1)
        self.proj_out = tnn.Conv2d(ch, ch, 1)

    def forward(self, x):
        b, c, h, w = x.shape
        hn = self.norm(x)
        q = self.q(hn).reshape(b, c, h * w).permute(0, 2, 1)
        k = self.k(hn).reshape(b, c, h * w)
        v = self.v(hn).reshape(b, c, h * w)
        a = torch.softmax(torch.bmm(q, k) * (c ** -0.5), dim=2)
        o = torch.bmm(v, a.permute(0, 2, 1)).reshape(b, c, h, w)
        return x + self.proj_out(o)


class TVAEMid(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.block_1 = TVAERes(ch, ch)
        self.attn_1 = TVAEAttn(ch)
        self.block_2 = TVAERes(ch, ch)

    def forward(self, x):
        return self.block_2(self.attn_1(self.block_1(x)))


class TVAEUpLevel(tnn.Module):
    def __init__(self, in_ch, out_ch, upsample):
        super().__init__()
        self.block = tnn.ModuleList(
            [TVAERes(in_ch if j == 0 else out_ch, out_ch) for j in range(3)])
        if upsample:
            self.upsample = TUp(out_ch)


class TVAEDecoder(tnn.Module):
    def __init__(self, ch=VAE_CH, mults=VAE_MULTS):
        super().__init__()
        top = ch * mults[-1]
        self.conv_in = tnn.Conv2d(4, top, 3, padding=1)
        self.mid = TVAEMid(top)
        ups = {}
        cur = top
        for i, m in reversed(list(enumerate(mults))):
            ups[i] = TVAEUpLevel(cur, ch * m, upsample=i != 0)
            cur = ch * m
        self.up = tnn.ModuleList([ups[i] for i in sorted(ups)])
        self.norm_out = gn(cur, 1e-6)
        self.conv_out = tnn.Conv2d(cur, 3, 3, padding=1)

    def forward(self, z):
        h = self.mid(self.conv_in(z))
        for i in reversed(range(len(self.up))):
            lvl = self.up[i]
            for blk in lvl.block:
                h = blk(h)
            if hasattr(lvl, "upsample"):
                h = lvl.upsample(h)
        return self.conv_out(F.silu(self.norm_out(h)))


class TVAE(tnn.Module):
    """first_stage_model: post_quant_conv + decoder (serving subset)."""

    def __init__(self):
        super().__init__()
        self.post_quant_conv = tnn.Conv2d(4, 4, 1)
        self.decoder = TVAEDecoder()

    def forward(self, z):
        return self.decoder(self.post_quant_conv(z))


# -- helpers -------------------------------------------------------------------

def nchw(x_nhwc: np.ndarray) -> torch.Tensor:
    return torch.from_numpy(x_nhwc).permute(0, 3, 1, 2).contiguous()


def to_nhwc(t: torch.Tensor) -> np.ndarray:
    return t.detach().permute(0, 2, 3, 1).numpy()


def tiny_sd_options() -> dict:
    return {
        "steps": 2, "vocab_size": VOCAB,
        "text_layers": TXT_LAYERS, "text_d_model": TXT_D, "text_heads": TXT_HEADS,
        "unet_ch": CH, "unet_mults": list(MULTS), "unet_res": NRES,
        "unet_attn_levels": list(ATTN), "unet_heads": HEADS,
        "vae_ch": VAE_CH, "vae_mults": list(VAE_MULTS),
    }


def model_vocab_size() -> int:
    """The served text tower's vocab is the tokenizer's (synthetic vocabs
    add base characters on top of options.vocab_size), so the torch CLIP
    reference must ask the model, not assume."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    probe = build(ModelConfig(name="sd", family="sd15", dtype="float32",
                              batch_buckets=[1], image_size=32,
                              options=tiny_sd_options()))
    return probe.text_encoder.vocab_size


# -- tests ---------------------------------------------------------------------

def test_clip_text_parity_vs_transformers():
    """Our CLIP tower vs transformers' torch CLIPTextModel — a fully
    independent implementation of the exact module SD checkpoints embed."""
    from transformers import CLIPTextConfig, CLIPTextModel

    from tpuserve.models.sd15 import CLIPTextEncoder

    seed_all()
    tc = CLIPTextConfig(
        vocab_size=VOCAB, hidden_size=TXT_D, intermediate_size=4 * TXT_D,
        num_hidden_layers=TXT_LAYERS, num_attention_heads=TXT_HEADS,
        max_position_embeddings=77, hidden_act="quick_gelu")
    ref = randomize(CLIPTextModel(tc))
    flat = sd_numpy(ref)

    ids = np.random.randint(0, VOCAB, size=(2, 77)).astype(np.int32)
    with torch.no_grad():
        want = ref(input_ids=torch.from_numpy(ids.astype(np.int64))
                   ).last_hidden_state.numpy()

    params = sd15_import.map_clip_text(
        flat, "text_model.", layers=TXT_LAYERS, heads=TXT_HEADS)
    enc = CLIPTextEncoder(vocab_size=VOCAB, layers=TXT_LAYERS, d_model=TXT_D,
                          heads=TXT_HEADS, dtype=jnp.float32)
    got = np.asarray(enc.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, **TOL)


def test_unet_parity_vs_ldm_reference():
    from tpuserve.models.sd15 import UNet

    seed_all()
    ref = randomize(TUNet())
    flat = sd_numpy(ref)

    x = np.random.randn(2, 8, 8, 4).astype(np.float32)
    t = np.array([3, 750], dtype=np.int32)
    ctx = np.random.randn(2, 77, TXT_D).astype(np.float32)
    with torch.no_grad():
        want = to_nhwc(ref(nchw(x), torch.from_numpy(t),
                           torch.from_numpy(ctx)))

    params = sd15_import.map_unet(
        flat, "", model_ch=CH, mults=MULTS, num_res=NRES, attn_levels=ATTN,
        heads=HEADS)
    unet = UNet(model_ch=CH, mults=MULTS, num_res=NRES, attn_levels=ATTN,
                heads=HEADS, dtype=jnp.float32)
    got = np.asarray(unet.apply({"params": params}, jnp.asarray(x),
                                jnp.asarray(t), jnp.asarray(ctx)))
    np.testing.assert_allclose(got, want, **TOL)


def test_vae_parity_vs_ldm_reference():
    from tpuserve.models.sd15 import VAEDecoder

    seed_all()
    ref = randomize(TVAE())
    flat = sd_numpy(ref)

    z = np.random.randn(2, 8, 8, 4).astype(np.float32)
    with torch.no_grad():
        want = to_nhwc(ref(nchw(z)))

    params = sd15_import.map_vae_decoder(flat, "", ch=VAE_CH, mults=VAE_MULTS)
    vae = VAEDecoder(ch=VAE_CH, mults=VAE_MULTS, dtype=jnp.float32)
    got = np.asarray(vae.apply({"params": params}, jnp.asarray(z)))
    np.testing.assert_allclose(got, want, **TOL)


def test_full_safetensors_checkpoint_end_to_end(tmp_path):
    """Assemble a complete tiny LDM-layout checkpoint (all three towers,
    real safetensors file), load through ModelConfig.weights ->
    extract_torch_state_dict -> import_torch_variables, and serve a
    forward — the path a user with v1-5-pruned.safetensors exercises."""
    from safetensors.torch import save_file
    from transformers import CLIPTextConfig, CLIPTextModel

    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    seed_all()
    tc = CLIPTextConfig(
        vocab_size=model_vocab_size(), hidden_size=TXT_D,
        intermediate_size=4 * TXT_D,
        num_hidden_layers=TXT_LAYERS, num_attention_heads=TXT_HEADS,
        max_position_embeddings=77, hidden_act="quick_gelu")
    towers = {}
    towers.update({f"cond_stage_model.transformer.{k}": v for k, v in
                   randomize(CLIPTextModel(tc)).state_dict().items()})
    towers.update({f"model.diffusion_model.{k}": v for k, v in
                   randomize(TUNet()).state_dict().items()})
    towers.update({f"first_stage_model.{k}": v for k, v in
                   randomize(TVAE()).state_dict().items()})
    path = str(tmp_path / "tiny_sd.safetensors")
    save_file({k: v.contiguous() for k, v in towers.items()}, path)

    cfg = ModelConfig(name="sd", family="sd15", dtype="float32",
                      batch_buckets=[1], image_size=32, weights=path,
                      options=tiny_sd_options())
    model = build(cfg)
    params = model.load_params()

    # Same leaf count/shapes as a fresh init (validated inside the import);
    # a forward through the whole DDIM loop executes and emits a PNG-able
    # uint8 image.
    item = model.host_decode(b'{"prompt": "a tpu", "seed": 7}',
                             "application/json")
    batch = model.assemble([item], (1,))
    out = jax.jit(model.forward)(params, batch)
    img = np.asarray(out["image"])
    assert img.shape == (1, 32, 32, 3) and img.dtype == np.uint8


def test_wrong_architecture_fails_with_guidance(tmp_path):
    """A checkpoint whose UNet width disagrees with the config must fail at
    import with an actionable message, not at compile."""
    from safetensors.torch import save_file
    from transformers import CLIPTextConfig, CLIPTextModel

    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    seed_all()
    tc = CLIPTextConfig(
        vocab_size=model_vocab_size(), hidden_size=TXT_D,
        intermediate_size=4 * TXT_D,
        num_hidden_layers=TXT_LAYERS, num_attention_heads=TXT_HEADS,
        max_position_embeddings=77, hidden_act="quick_gelu")
    towers = {}
    towers.update({f"cond_stage_model.transformer.{k}": v for k, v in
                   randomize(CLIPTextModel(tc)).state_dict().items()})
    towers.update({f"model.diffusion_model.{k}": v for k, v in
                   randomize(TUNet(ch=16)).state_dict().items()})  # wrong width
    towers.update({f"first_stage_model.{k}": v for k, v in
                   randomize(TVAE()).state_dict().items()})
    path = str(tmp_path / "wrong.safetensors")
    save_file({k: v.contiguous() for k, v in towers.items()}, path)

    cfg = ModelConfig(name="sd", family="sd15", dtype="float32",
                      batch_buckets=[1], image_size=32, weights=path,
                      options=tiny_sd_options())
    with pytest.raises(ValueError, match="shape|architecture"):
        build(cfg).load_params()


def test_non_ldm_checkpoint_rejected(tmp_path):
    from safetensors.torch import save_file

    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    path = str(tmp_path / "other.safetensors")
    save_file({"some.random.weight": torch.zeros(3, 3)}, path)
    cfg = ModelConfig(name="sd", family="sd15", dtype="float32",
                      batch_buckets=[1], image_size=32, weights=path,
                      options=tiny_sd_options())
    with pytest.raises(ValueError, match="LDM"):
        build(cfg).load_params()
