"""Test harness setup (SURVEY.md §4).

All unit tests run on CPU with 8 fake XLA devices so mesh/DP/TP logic is
exercised without TPU hardware (the standard JAX trick; SURVEY.md §4-3).
Environment must be set before jax imports — hence at conftest import time.
Set TPUSERVE_TEST_TPU=1 to run the suite against the real accelerator.
"""

import os

if not os.environ.get("TPUSERVE_TEST_TPU"):
    # Force CPU even when the environment pre-sets JAX_PLATFORMS (e.g. the
    # dev box exports JAX_PLATFORMS=axon for the tunneled TPU).
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

    # The dev box's sitecustomize registers the tunneled-TPU PJRT plugin and
    # calls jax.config.update("jax_platforms", "axon,cpu"), which overrides
    # the env var — undo it before any backend is initialized.
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def toy_cfg():
    from tpuserve.config import ModelConfig

    return ModelConfig(
        name="toy",
        family="toy",
        batch_buckets=[1, 2, 4],
        deadline_ms=10.0,
        dtype="float32",
        num_classes=10,
        parallelism="single",
    )
