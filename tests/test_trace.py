"""End-to-end request tracing (ISSUE 12; docs/OBSERVABILITY.md).

Covers the trace-context contract (mint/adopt, X-Trace-Id on EVERY
response, trace_id in error bodies), the flight recorder's bounds (ring
overflow keeps newest, slowest-N reservoir evicts the fastest under churn,
errored requests retained even when fast), chrome_trace JSON validity with
the documented event fields, single-flight trace links, and /metrics
exemplars — over real HTTP where the contract is user-facing.
"""

import asyncio
import io
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.cache import ModelCache
from tpuserve.config import CacheConfig, ModelConfig, ServerConfig, TraceConfig
from tpuserve.obs import (FlightRecorder, Metrics, TraceContext, Tracer,
                          spans_to_chrome, valid_trace_id)
from tpuserve.server import ServerState, make_app

# ---------------------------------------------------------------------------
# TraceContext
# ---------------------------------------------------------------------------


def test_trace_context_mints_valid_ids():
    a, b = TraceContext(), TraceContext()
    assert valid_trace_id(a.trace_id) and valid_trace_id(b.trace_id)
    assert a.trace_id != b.trace_id  # 128-bit mint: no collisions in two
    assert len(a.root_id) == 16


def test_trace_context_adopts_wellformed_header_only():
    tid = "ab" * 16
    ctx = TraceContext.from_headers({"X-Trace-Id": tid,
                                     "X-Parent-Span": "cd" * 8})
    assert ctx.trace_id == tid
    assert ctx.parent_id == "cd" * 8
    for junk in ("short", "Z" * 32, "AB" * 16, "ab" * 17, "", None, 42):
        bad = TraceContext(trace_id=junk)
        assert bad.trace_id != junk
        assert valid_trace_id(bad.trace_id)  # replaced, never echoed


def test_span_records_documented_fields():
    ctx = TraceContext(pid=3)
    sid = ctx.span("queue", 100.0, 100.25, tid="toy", batch=7)
    ctx.root_span("request", 99.0, 101.0, tid="toy", status=200)
    (queue, root) = ctx.spans
    for s in ctx.spans:
        assert set(s) == {"name", "trace_id", "span_id", "parent_id",
                          "ts_us", "dur_us", "tid", "pid", "args"}
        assert s["trace_id"] == ctx.trace_id
        assert s["pid"] == 3
    assert queue["span_id"] == sid
    assert queue["parent_id"] == ctx.root_id  # default parent = root
    assert queue["args"]["batch"] == 7
    assert root["span_id"] == ctx.root_id
    assert root["parent_id"] is None  # no upstream attempt relayed us
    assert abs(queue["dur_us"] - 250_000) < 1


def test_root_span_parents_under_relayed_attempt():
    parent = "ef" * 8
    ctx = TraceContext.from_headers({"X-Trace-Id": "12" * 16,
                                     "X-Parent-Span": parent})
    ctx.root_span("request", 0.0, 1.0, tid="toy")
    assert ctx.spans[0]["parent_id"] == parent


# ---------------------------------------------------------------------------
# Tracer ring bounds + chrome output
# ---------------------------------------------------------------------------


def test_ring_overflow_keeps_newest():
    t = Tracer(capacity=8)
    for i in range(50):
        t.add(f"e{i}", float(i), float(i) + 0.1, tid="m")
    names = [e["name"] for e in json.loads(t.chrome_trace())["traceEvents"]]
    assert names == [f"e{i}" for i in range(42, 50)]


def test_chrome_trace_limit_and_since_us():
    t = Tracer(capacity=64)
    for i in range(20):
        t.add(f"e{i}", float(i), float(i) + 0.1)
    evs = json.loads(t.chrome_trace(limit=3))["traceEvents"]
    assert [e["name"] for e in evs] == ["e17", "e18", "e19"]  # newest
    evs = json.loads(t.chrome_trace(since_us=15e6))["traceEvents"]
    assert [e["name"] for e in evs] == [f"e{i}" for i in range(15, 20)]
    assert json.loads(t.chrome_trace(limit=0))["traceEvents"] == []


def test_chrome_trace_event_fields_valid_json():
    t = Tracer()
    t.add("batch[(2, 8)]", 100.0, 100.5, tid="toy", trace_id="ab" * 16,
          pid=2, n=2, trace_ids=["ab" * 16])
    data = json.loads(t.chrome_trace())
    (ev,) = data["traceEvents"]
    assert set(ev) == {"name", "ph", "ts", "dur", "pid", "tid", "args"}
    assert ev["ph"] == "X" and ev["pid"] == 2 and ev["tid"] == "toy"
    assert ev["args"]["trace_id"] == "ab" * 16
    assert ev["args"]["trace_ids"] == ["ab" * 16]


def test_spans_to_chrome_documented_fields():
    ctx = TraceContext(pid=1)
    ctx.span("compute", 100.2, 100.4, tid="toy", batch=3)
    ctx.root_span("request", 100.0, 100.5, tid="toy", status=200)
    data = json.loads(spans_to_chrome(ctx.spans))
    evs = data["traceEvents"]
    assert [e["name"] for e in evs] == ["request", "compute"]  # ts-sorted
    for e in evs:
        assert set(e) == {"name", "ph", "ts", "dur", "pid", "tid", "args"}
        assert e["ph"] == "X" and e["pid"] == 1
        assert e["args"]["trace_id"] == ctx.trace_id
        assert "span_id" in e["args"] and "parent_id" in e["args"]


# ---------------------------------------------------------------------------
# FlightRecorder bounds
# ---------------------------------------------------------------------------


def _ctx_with_span(dur_ms: float = 1.0) -> TraceContext:
    ctx = TraceContext()
    ctx.root_span("request", 100.0, 100.0 + dur_ms / 1e3, tid="toy")
    return ctx


def test_slow_reservoir_keeps_slowest_under_churn():
    fr = FlightRecorder(slow_n=4, error_capacity=8)
    rng = np.random.default_rng(0)
    durations = list(rng.permutation(50).astype(float) + 1.0)
    ids = {}
    for d in durations:
        ctx = _ctx_with_span(d)
        ids[d] = ctx.trace_id
        fr.finish(ctx, "toy", 200, d)
    dump = fr.dump()
    kept = [r["duration_ms"] for r in dump["slow"]["toy"]]
    assert kept == sorted(durations, reverse=True)[:4]  # slowest-first
    # Retained records resolve by id; evicted (fast) ones are gone.
    assert fr.get(ids[max(durations)]) is not None
    assert fr.get(ids[min(durations)]) is None
    assert fr.stats()["slow"]["toy"] == 4


def test_slow_reservoirs_are_per_model():
    fr = FlightRecorder(slow_n=2, error_capacity=0,
                        always_record_errors=False)
    for model in ("a", "b"):
        for d in (5.0, 10.0, 1.0):
            fr.finish(_ctx_with_span(d), model, 200, d)
    dump = fr.dump()
    assert [r["duration_ms"] for r in dump["slow"]["a"]] == [10.0, 5.0]
    assert [r["duration_ms"] for r in dump["slow"]["b"]] == [10.0, 5.0]
    assert fr.dump(model="a")["slow"].keys() == {"a"}


def test_errored_requests_retained_even_when_fast():
    fr = FlightRecorder(slow_n=2, error_capacity=8)
    # Fill the slow reservoir with slow successes...
    for d in (500.0, 400.0):
        fr.finish(_ctx_with_span(d), "toy", 200, d)
    # ...then a FAST shed: far too quick for the slow reservoir, but
    # errors record unconditionally.
    ctx = _ctx_with_span(0.2)
    fr.finish(ctx, "toy", 503, 0.2)
    assert fr.get(ctx.trace_id) is not None
    dump = fr.dump()
    assert [r["status"] for r in dump["errors"]] == [503]
    assert all(r["status"] == 200 for r in dump["slow"]["toy"])


def test_error_fifo_bounded_newest_kept():
    fr = FlightRecorder(slow_n=0, error_capacity=3)
    ids = []
    for i in range(7):
        ctx = _ctx_with_span(1.0)
        ids.append(ctx.trace_id)
        fr.finish(ctx, "toy", 500, 1.0)
    dump = fr.dump()
    assert [r["trace_id"] for r in dump["errors"]] == ids[-1:-4:-1]
    assert fr.get(ids[0]) is None  # evicted from the FIFO
    assert fr.get(ids[-1]) is not None


def test_record_in_both_reservoirs_survives_single_eviction():
    """A slow ERROR sits in both reservoirs; falling out of one must not
    drop it from /debug/trace while the other still holds it."""
    fr = FlightRecorder(slow_n=2, error_capacity=16)
    slow_err = _ctx_with_span(900.0)
    fr.finish(slow_err, "toy", 504, 900.0)
    # Push it out of the slow heap with two even slower successes.
    for d in (1000.0, 1100.0):
        fr.finish(_ctx_with_span(d), "toy", 200, d)
    rec = fr.get(slow_err.trace_id)
    assert rec is not None and rec["status"] == 504  # error FIFO holds it
    assert all(r["status"] == 200
               for r in fr.dump()["slow"]["toy"])


def test_always_record_errors_off():
    fr = FlightRecorder(slow_n=0, error_capacity=8,
                        always_record_errors=False)
    ctx = _ctx_with_span(1.0)
    assert not fr.finish(ctx, "toy", 500, 1.0)
    assert fr.get(ctx.trace_id) is None


def test_recorder_dump_and_records_are_json_clean():
    fr = FlightRecorder(slow_n=2, error_capacity=2)
    ctx = _ctx_with_span(5.0)
    fr.finish(ctx, "toy", 200, 5.0)
    dump = json.loads(json.dumps(fr.dump()))  # must round-trip
    rec = dump["slow"]["toy"][0]
    assert set(rec) == {"trace_id", "model", "status", "duration_ms", "ts",
                        "spans"}  # no private retention flags leak
    assert rec["spans"][0]["name"] == "request"


def test_recorder_ticks_trace_recorded_counters():
    m = Metrics()
    fr = FlightRecorder(slow_n=2, error_capacity=2, metrics=m)
    fr.finish(_ctx_with_span(5.0), "toy", 200, 5.0)
    fr.finish(_ctx_with_span(1.0), "toy", 503, 1.0)
    assert m.counter('trace_recorded_total{model=toy,kind=slow}').value == 2
    assert m.counter('trace_recorded_total{model=toy,kind=error}').value == 1


# ---------------------------------------------------------------------------
# Single-flight trace links (tpuserve.cache)
# ---------------------------------------------------------------------------


def test_coalesced_waiter_links_leader_trace():
    async def go():
        m = Metrics()
        cache = ModelCache("toy", CacheConfig(enabled=True), m,
                           version_fn=lambda: 1)
        loop = asyncio.get_running_loop()
        base: asyncio.Future = loop.create_future()
        leader, waiter = TraceContext(), TraceContext()
        w1 = cache.submit_through("k", lambda: base, ctx=leader)
        w2 = cache.submit_through("k", lambda: 1 / 0, ctx=waiter)
        link = [s for s in waiter.spans if s["name"] == "coalesced"]
        assert len(link) == 1
        assert link[0]["args"]["linked_trace"] == leader.trace_id
        assert not leader.spans  # the leader records nothing extra
        base.set_result({"ok": 1})
        assert await w1 == {"ok": 1} and await w2 == {"ok": 1}

    asyncio.new_event_loop().run_until_complete(go())


# ---------------------------------------------------------------------------
# Over HTTP: the user-facing contract
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def client(loop):
    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single", request_timeout_ms=10_000.0,
                            wire_size=8)],
        decode_threads=2,
        trace=TraceConfig(slow_n=8, error_capacity=32),
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def setup():
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    c = loop.run_until_complete(setup())
    yield lambda coro: loop.run_until_complete(coro), c, state
    loop.run_until_complete(c.close())


def npy_bytes(seed: int = 0) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (8, 8, 3), dtype=np.uint8))
    return buf.getvalue()


NPY = "application/x-npy"


def test_every_response_carries_trace_id(client):
    run, c, state = client

    async def go():
        seen = set()
        # success, unknown model (404), malformed body (400)
        for path, data, ctype in (
                ("/v1/models/toy:predict", npy_bytes(), NPY),
                ("/v1/models/ghost:predict", npy_bytes(), NPY),
                ("/v1/models/toy:predict", b"garbage", NPY)):
            resp = await c.post(path, data=data,
                                headers={"Content-Type": ctype})
            tid = resp.headers.get("X-Trace-Id")
            assert valid_trace_id(tid), (path, resp.status, tid)
            seen.add(tid)
        assert len(seen) == 3  # every request gets its own id

    run(go())


def test_client_supplied_trace_id_adopted(client):
    run, c, state = client
    tid = "5a" * 16

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=npy_bytes(),
                            headers={"Content-Type": NPY, "X-Trace-Id": tid})
        assert resp.status == 200
        assert resp.headers["X-Trace-Id"] == tid
        # Malformed client ids are REPLACED, not echoed.
        resp = await c.post("/v1/models/toy:predict", data=npy_bytes(),
                            headers={"Content-Type": NPY,
                                     "X-Trace-Id": "not-hex!"})
        assert resp.status == 200
        assert valid_trace_id(resp.headers["X-Trace-Id"])
        assert resp.headers["X-Trace-Id"] != "not-hex!"

    run(go())


def test_error_bodies_carry_trace_id(client):
    """ISSUE 12 satellite: 400/429/503/504 JSON bodies carry a trace_id
    matching the X-Trace-Id header, so a shed/504'd user report joins
    directly against the flight recorder."""
    run, c, state = client

    async def go():
        statuses = {}

        async def check(resp, want):
            assert resp.status == want, await resp.text()
            body = await resp.json()
            assert valid_trace_id(body.get("trace_id")), (want, body)
            assert body["trace_id"] == resp.headers["X-Trace-Id"]
            statuses[want] = body["trace_id"]

        # 400: undecodable body.
        await check(await c.post("/v1/models/toy:predict", data=b"junk",
                                 headers={"Content-Type": NPY}), 400)
        # 429: queue full (force the shed check to fire).
        b = state.batchers["toy"]
        saved = b._pending
        b._pending = b.cfg.max_queue
        try:
            await check(await c.post("/v1/models/toy:predict",
                                     data=npy_bytes(),
                                     headers={"Content-Type": NPY}), 429)
        finally:
            b._pending = saved
        # 503: draining.
        state.draining = True
        try:
            await check(await c.post("/v1/models/toy:predict",
                                     data=npy_bytes(),
                                     headers={"Content-Type": NPY}), 503)
        finally:
            state.draining = False
        # 504: already-expired deadline.
        await check(await c.post("/v1/models/toy:predict?timeout_ms=0.01",
                                 data=npy_bytes(),
                                 headers={"Content-Type": NPY}), 504)
        # Every one of those landed in the flight recorder's error FIFO.
        for want, tid in statuses.items():
            rec = state.recorder.get(tid)
            assert rec is not None and rec["status"] == want

    run(go())


def test_slow_dump_has_complete_span_tree(client):
    run, c, state = client

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=npy_bytes(3),
                            headers={"Content-Type": NPY})
        assert resp.status == 200
        tid = resp.headers["X-Trace-Id"]
        async with c.get("/debug/slow") as r:
            assert r.status == 200
            dump = await r.json()
        recs = {rec["trace_id"]: rec for rec in dump["slow"]["toy"]}
        assert tid in recs
        names = {s["name"] for s in recs[tid]["spans"]}
        # The full serving path: HTTP ingest -> dispatch -> batcher phases.
        assert {"request", "body_read", "parse", "dispatch", "queue",
                "preproc", "h2d", "compute", "postproc"} <= names
        spans = recs[tid]["spans"]
        assert all(s["trace_id"] == tid for s in spans)
        # Phase spans carry the batch id they rode in.
        batch_ids = {s["args"]["batch"] for s in spans
                     if s["name"] == "compute"}
        assert len(batch_ids) == 1
        # /stats exposes reservoir occupancy.
        async with c.get("/stats") as r:
            stats = await r.json()
        assert stats["trace"]["slow"]["toy"] >= 1

    run(go())


def test_trace_endpoint_by_id_and_ring_limits(client):
    run, c, state = client

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=npy_bytes(4),
                            headers={"Content-Type": NPY})
        tid = resp.headers["X-Trace-Id"]
        # One recorded tree, Chrome format (valid JSON, documented fields).
        async with c.get(f"/debug/trace?trace_id={tid}") as r:
            assert r.status == 200
            data = json.loads(await r.text())
        assert {e["name"] for e in data["traceEvents"]} >= {"request",
                                                            "compute"}
        # Raw record form (what the router stitches).
        async with c.get(f"/debug/trace?trace_id={tid}&format=record") as r:
            rec = await r.json()
        assert rec["trace_id"] == tid and rec["spans"]
        # Unknown id -> 404, not an empty 200.
        async with c.get(f"/debug/trace?trace_id={'0' * 32}") as r:
            assert r.status == 404
        # Ring dump honors ?limit= (satellite: default 5000, never the
        # whole ring on a loaded server) and rejects junk.
        async with c.get("/debug/trace?limit=2") as r:
            ring = json.loads(await r.text())
        assert len(ring["traceEvents"]) <= 2
        async with c.get("/debug/trace?limit=nope") as r:
            assert r.status == 400
        async with c.get("/debug/trace?limit=-1") as r:
            assert r.status == 400
        async with c.get("/debug/trace?since_us=99999999999999999") as r:
            assert json.loads(await r.text())["traceEvents"] == []

    run(go())


def test_metrics_exemplars_over_http(client):
    run, c, state = client

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=npy_bytes(5),
                            headers={"Content-Type": NPY})
        tid = resp.headers["X-Trace-Id"]
        async with c.get("/metrics") as r:
            text = await r.text()
        ex_lines = [ln for ln in text.splitlines() if "# {trace_id=" in ln]
        assert ex_lines, "no exemplar lines on /metrics"
        # Every exemplar is a well-formed trace id on a histogram bucket
        # line; the latest request's id appears on its total-latency bucket.
        import re

        pat = re.compile(
            r'_bucket\{.*le="[^"]+"\} \d+ '
            r'# \{trace_id="([0-9a-f]{32})"\} [0-9.e+-]+ \d+\.\d+$')
        assert all(pat.search(ln) for ln in ex_lines), ex_lines[:3]
        assert any(tid in ln and "phase=\"total\"" in ln.replace('\\', '')
                   for ln in ex_lines)

    run(go())
