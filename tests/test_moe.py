"""Switch MoE (tpuserve.ops.moe) + expert parallelism on the fake-8 mesh.

Correctness bar: with ample capacity the static dispatch/combine formulation
must equal the obvious per-token reference (gate * chosen expert's FFN);
over-capacity tokens drop to zero (the residual passes them through); the
train step runs with the expert dim really sharded over "model" (EP).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuserve.ops.moe import SwitchFFN, switch_route

pytestmark = pytest.mark.slow


def _reference(x, router, w_up, w_down):
    """Per-token loop: y[t] = gate[t] * FFN_{argmax expert}(x[t])."""
    t, d = x.shape
    logits = x @ router
    gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    out = np.zeros_like(x)
    for i in range(t):
        e = int(np.argmax(gates[i]))
        h = np.asarray(jax.nn.gelu(jnp.asarray(x[i] @ w_up[e])))
        out[i] = gates[i, e] * (h @ w_down[e])
    return out


def test_matches_per_token_reference():
    rng = np.random.default_rng(0)
    b, s, d, f, e = 2, 8, 8, 16, 4
    x = rng.normal(size=(b, s, d)).astype(np.float32)
    mod = SwitchFFN(experts=e, d_ff=f, capacity_factor=8.0)  # no drops
    params = mod.init(jax.random.key(0), jnp.asarray(x))
    y, aux = mod.apply(params, jnp.asarray(x))
    p = params["params"]
    ref = _reference(x.reshape(-1, d), np.asarray(p["router"]),
                     np.asarray(p["w_up"]), np.asarray(p["w_down"]))
    np.testing.assert_allclose(np.asarray(y).reshape(-1, d), ref,
                               rtol=1e-5, atol=1e-5)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_over_capacity_tokens_drop_to_zero():
    """capacity 1 slot/expert: late-arriving tokens routed to a full expert
    contribute exactly zero (residual passthrough at the block level)."""
    t, e = 16, 2
    logits = jnp.asarray(np.zeros((t, e), np.float32))
    logits = logits.at[:, 0].set(5.0)  # everyone wants expert 0
    dispatch, combine, _ = switch_route(logits, capacity=1)
    assert float(dispatch.sum()) == 1.0  # only the first token fits
    assert float(combine[1:].sum()) == 0.0


def test_aux_is_one_for_perfect_balance():
    """Uniform routing: aux = E * sum(1/E * 1/E * E) = 1 (Switch eq. 4)."""
    t, e = 8, 4
    logits = jnp.asarray(np.eye(e, dtype=np.float32)[np.arange(t) % e] * 9.0)
    _, _, aux = switch_route(logits, capacity=t)
    np.testing.assert_allclose(float(aux), 1.0, atol=0.05)


def test_train_step_with_expert_parallelism():
    """moe_experts=4 over the dp/tp/sp mesh: expert weights shard on
    "model" (EP), the step runs, and the loss decreases."""
    from jax.sharding import PartitionSpec as P

    from tpuserve.parallel import make_mesh
    from tpuserve.train import (
        TrainConfig,
        make_train_state,
        make_train_step,
        mesh_plan_for,
        synthetic_batch,
    )

    mesh = make_mesh(mesh_plan_for(8))
    cfg = TrainConfig(n_layers=1, d_model=32, d_ff=64, vocab=64, max_seq=16,
                      moe_experts=4)
    model, params, tx, opt_state, shardings = make_train_state(mesh, cfg)
    assert params["block0"]["moe"]["w_up"].sharding.spec == P("model", None, None)
    assert params["block0"]["moe"]["w_up"].shape == (4, 32, 64)
    step, _ = make_train_step(model, tx, mesh, shardings)
    losses = []
    batch = synthetic_batch(cfg, 8, seed=0)
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state, dict(batch))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_padding_never_claims_capacity():
    """Masked tokens get zero output, consume no expert slots, and real
    tokens route identically with or without trailing padding."""
    rng = np.random.default_rng(5)
    b, s, d, f, e = 1, 8, 8, 16, 2
    x = rng.normal(size=(b, s, d)).astype(np.float32)
    mod = SwitchFFN(experts=e, d_ff=f, capacity_factor=1.0)  # tight capacity
    params = mod.init(jax.random.key(0), jnp.asarray(x))

    mask = np.ones((b, s), np.float32)
    mask[:, 4:] = 0.0  # tail is padding
    y_masked, _ = mod.apply(params, jnp.asarray(x), jnp.asarray(mask))
    assert float(np.abs(np.asarray(y_masked)[:, 4:]).sum()) == 0.0

    # At FIXED capacity, a masked full-length route must assign the real
    # prefix exactly like routing the prefix alone — padding is invisible
    # to the queues.
    logits = rng.normal(size=(s, e)).astype(np.float32)
    cap = 2
    d_full, c_full, _ = switch_route(jnp.asarray(logits), cap,
                                     jnp.asarray(mask[0]))
    d_pref, c_pref, _ = switch_route(jnp.asarray(logits[:4]), cap)
    np.testing.assert_allclose(np.asarray(d_full)[:4], np.asarray(d_pref))
    np.testing.assert_allclose(np.asarray(c_full)[:4], np.asarray(c_pref))
    assert float(np.asarray(d_full)[4:].sum()) == 0.0  # pads claim nothing


def test_moe_bert_serves_single_device():
    """options.moe_experts makes the bert family serve a Switch-MoE FFN;
    padded lanes must not perturb real lanes (per-row routing + token
    masking)."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.runtime import build_runtime

    cfg = ModelConfig(
        name="moe-bert", family="bert", parallelism="single",
        batch_buckets=[4], seq_buckets=[16], dtype="float32", num_classes=4,
        options={"layers": 1, "d_model": 32, "heads": 2, "d_ff": 64,
                 "vocab_size": 512, "moe_experts": 4},
    )
    model = build(cfg)
    rt = build_runtime(model)
    (bucket,) = rt.executables
    item = model.host_decode(b'{"text": "mixture of experts"}',
                             "application/json")
    out1 = rt.fetch(rt.run(bucket, model.assemble([item], bucket)))
    out2 = rt.fetch(rt.run(bucket, model.assemble([item, item, item], bucket)))
    assert np.isfinite(out1["probs"]).all()
    # Row 0's result must not depend on how many padded lanes ride along.
    np.testing.assert_allclose(out1["probs"][0], out2["probs"][0],
                               rtol=1e-5, atol=1e-6)


def test_moe_bert_expert_parallel_sharded():
    """EP serving: expert weights shard over the mesh's model axis and the
    sharded forward matches the single-device reference."""
    import jax

    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.parallel import make_mesh
    from tpuserve.parallel.mesh import MeshPlan
    from tpuserve.runtime import build_runtime

    if len(jax.devices()) < 4:
        pytest.skip("needs multi-device mesh")
    mesh = make_mesh(MeshPlan(tp=2), devices=jax.devices()[:4])
    cfg = ModelConfig(
        name="moe-bert", family="bert", parallelism="sharded", tp=2,
        batch_buckets=[2], seq_buckets=[16], dtype="float32", num_classes=4,
        options={"layers": 1, "d_model": 32, "heads": 2, "d_ff": 64,
                 "vocab_size": 512, "moe_experts": 4},
    )
    model = build(cfg)
    rt = build_runtime(model, mesh=mesh)
    # The (E, D, F) expert weights really are sharded on "model".
    from tpuserve.parallel.partition import named_leaves

    w_up = [leaf for name, leaf in named_leaves(rt.params_per_mesh[0])
            if "moe/w_up" in name]
    assert w_up and "model" in str(w_up[0].sharding.spec)
    (bucket,) = rt.executables
    item = model.host_decode(b'{"text": "expert parallel serving"}',
                             "application/json")
    out = rt.fetch(rt.run(bucket, model.assemble([item, item], bucket)))
    assert np.isfinite(out["probs"]).all()


def test_moe_experts_must_divide_tp():
    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    with pytest.raises(ValueError, match="divide"):
        build(ModelConfig(
            name="bad", family="bert", parallelism="sharded", tp=2,
            batch_buckets=[2], seq_buckets=[16], num_classes=4,
            options={"layers": 1, "d_model": 32, "heads": 2, "d_ff": 64,
                     "vocab_size": 512, "moe_experts": 3}))


def test_moe_experts_rejects_tf_weights():
    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    with pytest.raises(ValueError, match="moe_experts cannot be combined"):
        build(ModelConfig(
            name="bad", family="bert", weights="/nonexistent/savedmodel",
            batch_buckets=[2], seq_buckets=[16], num_classes=4,
            options={"layers": 1, "d_model": 32, "heads": 2, "d_ff": 64,
                     "vocab_size": 512, "moe_experts": 4}))
