"""Mesh construction + partition rules on the 8-fake-device CPU mesh (C7)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuserve.parallel import make_mesh, match_partition_rules, shard_pytree
from tpuserve.parallel.mesh import MeshPlan, pad_batch_to_mesh


def test_fake_devices_present():
    assert len(jax.devices()) == 8, "conftest must provide 8 fake CPU devices"


def test_make_mesh_default_dp():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1
    assert mesh.shape["seq"] == 1


def test_make_mesh_tp():
    mesh = make_mesh(MeshPlan(tp=2))
    assert mesh.shape["data"] == 4
    assert mesh.shape["model"] == 2


def test_mesh_plan_invalid():
    with pytest.raises(ValueError):
        MeshPlan(tp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshPlan(dp=3, tp=2).resolve(8)


def test_match_partition_rules():
    params = {
        "layer1": {"kernel": np.zeros((4, 8)), "bias": np.zeros((8,))},
        "head": {"kernel": np.zeros((8, 16))},
        "scalar": np.float32(1.0),
    }
    rules = [
        (r"head/kernel", P(None, "model")),
        (r".*bias", P()),
        (r".*kernel", P("model", None)),
        (r".*", P()),
    ]
    specs = match_partition_rules(rules, params)
    assert specs["head"]["kernel"] == P(None, "model")
    assert specs["layer1"]["kernel"] == P("model", None)
    assert specs["layer1"]["bias"] == P()
    assert specs["scalar"] == P()  # scalars never partitioned


def test_match_partition_rules_unmatched_raises():
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules([(r"xyz", P())], {"a": np.zeros((2, 2))})


def test_shard_pytree_places_on_mesh():
    mesh = make_mesh(MeshPlan(tp=2))
    params = {"w": np.ones((16, 4), np.float32), "b": np.zeros((4,), np.float32)}
    rules = [(r"w", P("model", None)), (r".*", P())]
    sharded = shard_pytree(params, rules, mesh)
    assert sharded["w"].sharding.spec == P("model", None)
    # value integrity after sharding
    np.testing.assert_array_equal(np.asarray(sharded["w"]), params["w"])


def test_sharded_matmul_matches_single_device():
    """DP+TP sharded execution must be numerically identical to unsharded."""
    mesh = make_mesh(MeshPlan(tp=2))
    x = np.random.default_rng(0).normal(size=(16, 32)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(32, 64)).astype(np.float32)

    from jax.sharding import NamedSharding

    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    ws = jax.device_put(w, NamedSharding(mesh, P(None, "model")))
    f = jax.jit(lambda a, b: a @ b, out_shardings=NamedSharding(mesh, P("data", "model")))
    out = np.asarray(f(xs, ws))
    np.testing.assert_allclose(out, x @ w, rtol=1e-5)


def test_pad_batch_to_mesh():
    mesh = make_mesh()
    assert pad_batch_to_mesh(1, mesh) == 8
    assert pad_batch_to_mesh(8, mesh) == 8
    assert pad_batch_to_mesh(9, mesh) == 16
