"""Host failure domains (ISSUE 13): real host-agent subprocesses, each
owning its worker fleet in its own process group.

Layers of coverage, like test_router.py all against REAL processes:

- pure units: host-aware pick (hedge never lands on the primary's host),
  the host breaker trip/half-open machine, consistent wid -> host math;
- a module-scoped host fleet (2 hosts x 2 workers, toy model) proving the
  topology boots and serves, a SINGLE worker death is a HOST-local event
  (the agent respawns it; the router just learns the new port), and the
  tentpole sequence: killpg one entire host mid-serving -> requests keep
  answering on the survivor -> a fleet :reload is REFUSED 409 with
  per-host outcomes while the domain is down -> the host re-absorbs and
  a reload then succeeds fleet-wide.

No pytest-asyncio in the image: a module-level event loop drives
everything explicitly (the test_router idiom).
"""

import asyncio
import io
import os
import signal
import time

import numpy as np
import pytest

from tpuserve.config import ModelConfig, RouterConfig, ServerConfig

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning")

NPY = "application/x-npy"


def npy(seed: int = 0, edge: int = 8) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 255, (edge, edge, 3), dtype=np.uint8))
    return buf.getvalue()


def _toy(name: str, **kw) -> ModelConfig:
    base = dict(family="toy", batch_buckets=[1, 2], deadline_ms=2.0,
                dtype="float32", num_classes=10, parallelism="single",
                request_timeout_ms=10_000.0, wire_size=8, max_inflight=2)
    base.update(kw)
    return ModelConfig(name=name, **base)


def _parse_metrics(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        k, v = line.rsplit(" ", 1)
        try:
            out[k] = float(v)
        except ValueError:
            pass
    return out


@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# ---------------------------------------------------------------------------
# Pure units (no processes spawned)
# ---------------------------------------------------------------------------

def _bare_supervisor(hosts=2, workers=2):
    """A HostSupervisor with hand-built refs and NO processes: pick() and
    the breaker never touch the agent handles' procs."""
    from tpuserve.obs import Metrics
    from tpuserve.workerproc.hosts import HostHandle, HostSupervisor, WorkerRef

    cfg = ServerConfig(
        models=[_toy("toy")],
        router=RouterConfig(enabled=True, workers=workers, hosts=hosts,
                            host_breaker_threshold=2,
                            host_breaker_cooldown_s=0.2))
    sup = HostSupervisor(cfg, Metrics(16))
    for hid in range(hosts):
        h = object.__new__(HostHandle)
        h.hid = hid
        h.pgid = h.pid = 1000 + hid
        h.proc = type("P", (), {"is_alive": lambda self: True})()
        h.conn = None
        h.workers = {}
        h.started_at = time.monotonic()
        for wid in sup._host_wids(hid):
            ref = WorkerRef(wid, hid, 9000 + wid, 2000 + wid, "127.0.0.1")
            h.workers[wid] = ref
            sup._refs[wid] = ref
        sup.hosts[hid] = h
    return sup


def test_pick_excludes_whole_hosts():
    """The hedge rule: pick(exclude_hosts={primary's host}) never returns a
    worker on that host, and returns None when every other domain is
    excluded — the relay then simply doesn't hedge."""
    sup = _bare_supervisor(hosts=2, workers=2)
    w = sup.pick(exclude_hosts={0})
    assert w is not None and w.host == 1
    assert sup.pick(exclude_hosts={0, 1}) is None
    # exclude wids composes with exclude_hosts
    other = sup.pick(exclude={w.wid}, exclude_hosts={0})
    assert other is not None and other.host == 1 and other.wid != w.wid


def test_pick_is_least_loaded_across_hosts():
    sup = _bare_supervisor(hosts=2, workers=2)
    for wid, ref in sup._refs.items():
        ref.inflight = 5 if ref.host == 0 else 1
    assert sup.pick().host == 1


def test_host_breaker_trips_and_half_opens():
    """threshold consecutive transport failures shed the WHOLE host from
    pick(); after the cooldown the next pick is the probe, and a success
    closes it."""
    sup = _bare_supervisor(hosts=2, workers=2)
    victim = sup.hosts[0].workers[0]
    assert not sup.host_tripped(0)
    sup.note_transport_failure(victim)
    assert not sup.host_tripped(0)  # threshold 2
    sup.note_transport_failure(victim)
    assert sup.host_tripped(0)
    assert all(w.host == 1 for w in [sup.pick() for _ in range(4)])
    time.sleep(0.25)  # cooldown 0.2
    assert not sup.host_tripped(0)  # half-open: picks allowed again
    # a new failure re-trips immediately (fails still >= threshold)...
    sup.note_transport_failure(victim)
    assert sup.host_tripped(0)
    # ...and a success closes it outright.
    sup.note_success(victim)
    assert not sup.host_tripped(0)
    assert {sup.pick(exclude={w.wid for w in sup.healthy_workers()
                              if w.host == 1}).host} == {0}


def test_down_domains_names_hosts_and_agent_respawns():
    from tpuserve.workerproc.hosts import host_name

    sup = _bare_supervisor(hosts=2, workers=2)

    class DeadProc:
        def is_alive(self):
            return False

    for h in sup.hosts:
        h.proc = type("P", (), {"is_alive": lambda self: True})()
    assert sup.down_domains() == []
    sup.hosts[1].proc = DeadProc()
    assert sup.down_domains() == [host_name(1)]
    # a worker the agent is re-booting is its own (sub-)domain
    sup.hosts[0].workers[1].up = False
    assert set(sup.down_domains()) == {host_name(1), "host0:worker1"}


def test_recycle_rejected_at_host_supervisor_construction():
    from tpuserve.obs import Metrics
    from tpuserve.workerproc.hosts import HostSupervisor

    cfg = ServerConfig(models=[_toy("rc", session_mode="recycle")],
                       router=RouterConfig(enabled=True, hosts=2))
    with pytest.raises(ValueError, match="recycle"):
        HostSupervisor(cfg, Metrics(16))


# ---------------------------------------------------------------------------
# The host fleet (module-scoped: 2 real host agents x 2 real workers)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def hostfleet(loop):
    import aiohttp
    from aiohttp import web

    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg = ServerConfig(
        decode_threads=2, startup_canary=False, drain_timeout_s=3.0,
        watchdog_interval_s=0.2,
        router=RouterConfig(enabled=True, workers=2, hosts=2, retry_max=3,
                            hedge_ms=150.0, health_interval_s=0.2,
                            unhealthy_after=2, respawn_initial_s=0.3,
                            respawn_max_s=2.0),
        models=[_toy("toy")],
    )
    state = RouterState(cfg)
    runner = web.AppRunner(make_router_app(state), access_log=None)

    async def setup():
        await runner.setup()  # on_startup spawns agents + workers
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return aiohttp.ClientSession()

    session = loop.run_until_complete(setup())
    base = f"http://127.0.0.1:{runner.addresses[0][1]}"

    def run(coro):
        return loop.run_until_complete(coro)

    yield run, session, base, state

    async def teardown():
        await session.close()
        await runner.cleanup()

    loop.run_until_complete(teardown())


async def _post(session, base, model, body, timeout_ms=None, total=30.0):
    import aiohttp

    params = {"timeout_ms": str(timeout_ms)} if timeout_ms else None
    async with session.post(f"{base}/v1/models/{model}:classify", data=body,
                            params=params, headers={"Content-Type": NPY},
                            timeout=aiohttp.ClientTimeout(total=total)) as r:
        return r.status, await r.read(), dict(r.headers)


async def _wait_health(session, base, want="ok", budget=60.0):
    deadline = time.monotonic() + budget
    health = {}
    while time.monotonic() < deadline:
        async with session.get(f"{base}/healthz") as r:
            health = await r.json()
        if health.get("status") == want:
            return health
        await asyncio.sleep(0.2)
    return health


def test_host_topology_boots_and_serves(hostfleet):
    run, session, base, state = hostfleet

    async def go():
        status, body, _ = await _post(session, base, "toy", npy(1))
        assert status == 200, body
        async with session.get(f"{base}/healthz") as r:
            health = await r.json()
            assert r.status == 200 and health["status"] == "ok"
        assert health["hosts"] == {"configured": 2, "up": 2}
        async with session.get(f"{base}/stats") as r:
            stats = await r.json()
        w = stats["workers"]
        assert w["configured"] == 4 and w["healthy"] == 4
        assert w["hosts_up"] == 2 and w["hosts_configured"] == 2
        assert [h["name"] for h in w["hosts"]] == ["host0", "host1"]
        assert all(h["state"] == "up" and len(h["workers"]) == 2
                   for h in w["hosts"])
        assert stats["topology"]["hosts_configured"] == 2
        assert stats["topology"]["workers_per_domain"] == 2
        async with session.get(f"{base}/metrics") as r:
            m = _parse_metrics(await r.text())
        assert m.get('host_up{host="0"}') == 1.0
        assert m.get('host_up{host="1"}') == 1.0
        for wid in range(4):
            assert m.get(f'worker_up{{worker="{wid}"}}') == 1.0
        # every worker is a REAL process on a live host; the global-wid
        # proxy reaches each one's own introspection endpoints
        async with session.get(f"{base}/workers/3/stats") as r:
            assert r.status == 200
            assert "pipeline" in await r.json()
        # workers report the topology seam on their own /stats (ISSUE 13
        # satellite: parallel/distributed.process_info wired in)
        async with session.get(f"{base}/workers/0/stats") as r:
            topo = (await r.json())["topology"]
        assert topo["process_count"] == 1 and topo["worker_id"] == 0
        assert topo["platform"] == "cpu"

    run(go())


def test_single_worker_death_is_host_local(hostfleet):
    """SIGKILL one WORKER (not its host): the host agent respawns it and
    reports the new port up the pipe; the host never goes down and the
    router keeps serving throughout."""
    run, session, base, state = hostfleet

    async def go():
        h0 = state.supervisor.hosts[0]
        victim = h0.workers[1]
        old_pid = victim.pid
        os.kill(old_pid, signal.SIGKILL)
        # serve across the death — the survivor fleet absorbs
        for i in range(10):
            status, body, _ = await _post(session, base, "toy", npy(100 + i))
            assert status == 200, body
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            ref = state.supervisor.hosts[0].workers.get(1)
            if ref is not None and ref.up and ref.pid != old_pid \
                    and ref.healthy:
                break
            await asyncio.sleep(0.1)
        ref = state.supervisor.hosts[0].workers[1]
        assert ref.pid != old_pid and ref.up, (ref.pid, old_pid)
        # the HOST never died: same agent, zero host respawns
        assert state.supervisor.hosts[0] is h0
        async with session.get(f"{base}/metrics") as r:
            m = _parse_metrics(await r.text())
        assert m.get('host_respawns_total{host="0"}', 0.0) == 0.0
        assert m.get('worker_respawns_total{worker="1"}') == 1.0
        # the respawned worker actually serves
        status, _, _ = await _post(session, base, "toy", npy(111))
        assert status == 200

    run(go())


def test_host_kill_degrades_then_reabsorbs(hostfleet):
    """The tentpole sequence, in-test scale: killpg one ENTIRE host (agent
    + both workers — one syscall, a machine death). Requests keep
    answering on the survivor host; a fleet :reload is refused 409 with
    per-host outcomes while the domain is down (degraded-fleet contract);
    /healthz says degraded but stays 200 (an LB must not pull the
    replica); the domain re-absorbs within the backoff budget and a
    reload then succeeds fleet-wide."""
    run, session, base, state = hostfleet

    async def go():
        victim = state.supervisor.hosts[0]
        pgid = victim.pgid
        os.killpg(pgid, signal.SIGKILL)

        # 1) availability through the kill: every request answers 200.
        for i in range(20):
            status, body, _ = await _post(session, base, "toy", npy(200 + i))
            assert status == 200, (i, status, body)

        # 2) degraded-fleet reload: FAST 409, per-host outcomes, nobody
        # touched — the fleet stays on one version.
        t0 = time.monotonic()
        async with session.post(f"{base}/admin/models/toy:reload") as r:
            info = await r.json()
            assert r.status == 409, info
        assert time.monotonic() - t0 < 5.0, "degraded reload must not hang"
        assert "host0" in info["down"], info
        assert "per_host" in info
        async with session.get(f"{base}/admin/models/toy/versions") as r:
            vers = await r.json()
        live = {w["live_version"] for w in vers["workers"].values()}
        assert len(live) == 1, vers  # survivors still on ONE version

        # 3) /healthz: degraded, not down.
        health = await _wait_health(session, base, want="degraded",
                                    budget=10.0)
        assert health["status"] == "degraded", health
        assert health["hosts"]["up"] == 1

        # 4) re-absorb: agent + both workers back, healthz ok again.
        health = await _wait_health(session, base, want="ok", budget=90.0)
        assert health["status"] == "ok", health
        assert health["hosts"] == {"configured": 2, "up": 2}
        async with session.get(f"{base}/metrics") as r:
            m = _parse_metrics(await r.text())
        assert m.get('host_respawns_total{host="0"}') == 1.0
        assert m.get('host_up{host="0"}') == 1.0
        assert state.supervisor.hosts[0].pgid != pgid
        assert state.supervisor.host_deaths_total == 1
        assert state.supervisor.deaths_total >= 2  # both workers went too

        # 5) the healed fleet reloads atomically, per-host outcomes green.
        async with session.post(f"{base}/admin/models/toy:reload") as r:
            info = await r.json()
            assert r.status == 200, info
        assert info["fleet_consistent"] is True
        assert sorted(info["per_host"]) == ["host0", "host1"]
        assert len(info["workers"]) == 4
        status, _, _ = await _post(session, base, "toy", npy(250))
        assert status == 200

    run(go())


def test_fleet_scrape_degrades_stale_never_500(hostfleet):
    """Fleet-aggregation degradation (ISSUE 14 satellite): a healthy
    scrape sums counters EXACTLY across workers; SIGKILLing an entire
    host mid-poll stale-marks that domain's sources in /metrics/fleet
    and /stats/fleet — never a 5xx — and after the PR-13 respawn the
    scrape is whole again."""
    from tpuserve.telemetry.fleet import sum_counter

    run, session, base, state = hostfleet

    async def scrape():
        async with session.get(f"{base}/metrics/fleet") as r:
            text = await r.text()
            assert r.status == 200, text  # the never-5xx contract
        async with session.get(f"{base}/stats/fleet") as r:
            rollup = await r.json()
            assert r.status == 200, rollup
        return text, rollup

    async def go():
        # 1) healthy fleet: serve some traffic, then prove exact summing.
        for i in range(8):
            status, body, _ = await _post(session, base, "toy", npy(300 + i))
            assert status == 200, body
        merged, rollup = await scrape()
        per_worker = 0.0
        for wid in range(4):
            async with session.get(f"{base}/workers/{wid}/metrics") as r:
                assert r.status == 200
                per_worker += sum_counter(await r.text(), "requests_total",
                                          'model="toy"')
        fleet_sum = sum_counter(merged, "requests_total", 'model="toy"')
        assert fleet_sum == per_worker > 0, (fleet_sum, per_worker)
        assert rollup["models"]["toy"]["requests_total"] == fleet_sum
        assert rollup["stale"] == [] and rollup["down_domains"] == []
        assert all(v == "up" for v in rollup["sources"].values())
        # gauges are per-process, worker_up stays the router's own
        assert 'proc="worker0"' in merged
        # true fleet latency quantiles from the merged buckets
        assert rollup["models"]["toy"]["fleet_latency_p99_ms"] is not None

        # 2) kill host 1 (agent + workers, one process group) mid-poll.
        victim = state.supervisor.hosts[1]
        os.killpg(victim.pgid, signal.SIGKILL)
        merged, rollup = await scrape()  # immediately: must not 5xx
        stale = set(rollup["stale"])
        assert {"worker2", "worker3"} <= stale, rollup
        assert 'fleet_source_up{proc="worker2"} 0' in merged
        assert "# STALE worker2" in merged
        # the survivor host's counters still merge
        assert sum_counter(merged, "requests_total", 'model="toy"') > 0
        # availability through the scrape window
        status, body, _ = await _post(session, base, "toy", npy(333))
        assert status == 200, body

        # 3) recover: the domain re-absorbs and the scrape is whole.
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            merged, rollup = await scrape()
            if not rollup["stale"] and not rollup["down_domains"]:
                break
            await asyncio.sleep(0.5)
        assert rollup["stale"] == [], rollup
        assert all(v == "up" for v in rollup["sources"].values())
        # Respawned workers restart their counters at 0 — the merged sum
        # is the CURRENT fleet truth, smaller than before the kill; the
        # reset-aware compensation lives in the history layer
        # (TimeSeriesStore), not in the instantaneous merge. The healed
        # fleet still serves and still sums.
        status, _, _ = await _post(session, base, "toy", npy(334))
        assert status == 200
        merged, _ = await scrape()
        assert sum_counter(merged, "requests_total", 'model="toy"') > 0

    run(go())


def test_retry_after_reflects_min_respawn_eta(hostfleet):
    """With hosts respawning, respawn_eta_s() is the MINIMUM ETA across
    dead domains — the honest Retry-After when the whole fleet is down."""
    run, session, base, state = hostfleet
    sup = state.supervisor

    async def go():
        # Healthy fleet: the fallback is the health interval.
        assert sup.respawn_eta_s() == pytest.approx(
            state.rcfg.health_interval_s)
        sup._respawning.add(0)
        sup._next_up_at[0] = time.monotonic() + 7.0
        sup._respawning.add(1)
        sup._next_up_at[1] = time.monotonic() + 3.0
        try:
            assert 2.0 < sup.respawn_eta_s() <= 3.0
        finally:
            sup._respawning.clear()

    run(go())
