"""Load generator (C11): closed vs open loop, measurement-window clamping,
straggler exclusion. VERDICT.md r2 item 2 / ADVICE r1+r2: the docstring's
claims are now behavior, pinned here."""

import asyncio

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from tpuserve.bench.loadgen import run_load, run_load_open


def serve_with_delay(loop, delay_s: float):
    hits = {"n": 0}

    async def handler(request):
        hits["n"] += 1
        await asyncio.sleep(delay_s)
        return web.json_response({"ok": True})

    app = web.Application()
    app.router.add_post("/v1/models/m:predict", handler)
    server = TestServer(app)
    loop.run_until_complete(server.start_server())
    url = f"http://{server.host}:{server.port}/v1/models/m:predict"
    return server, url, hits


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def test_closed_loop_measures_latency_and_window(loop):
    server, url, _ = serve_with_delay(loop, 0.02)
    res = loop.run_until_complete(
        run_load(url, b"x", "application/octet-stream",
                 duration_s=0.5, concurrency=4, warmup_s=0.1))
    loop.run_until_complete(server.close())
    assert res.mode == "closed"
    assert res.n_ok > 0 and res.n_err == 0
    assert res.duration_s == pytest.approx(0.5, abs=1e-6)
    s = res.summary()
    assert s["p50_ms"] >= 20.0  # can't be faster than the handler
    # throughput divides by the actual window, not request count tricks
    assert s["throughput_per_s"] == pytest.approx(res.n_ok / 0.5, rel=1e-6)


def test_closed_loop_excludes_stragglers(loop):
    """Completions after the window close land in n_late, never in n_ok."""
    server, url, _ = serve_with_delay(loop, 0.3)
    res = loop.run_until_complete(
        run_load(url, b"x", "application/octet-stream",
                 duration_s=0.45, concurrency=4, warmup_s=0.0))
    loop.run_until_complete(server.close())
    # Round 1 completes at ~0.3 (inside), round 2 at ~0.6 (outside).
    assert res.n_ok == 4
    assert res.n_late == 4


def test_open_loop_issues_on_a_clock(loop):
    """Offered rate is held regardless of completions; latency is server
    latency, not Little's-law queueing."""
    server, url, hits = serve_with_delay(loop, 0.03)
    res = loop.run_until_complete(
        run_load_open(url, b"x", "application/octet-stream",
                      rate_per_s=50.0, duration_s=1.0, warmup_s=0.2))
    loop.run_until_complete(server.close())
    assert res.mode == "open"
    s = res.summary()
    assert s["offered_rate_per_s"] == 50.0
    # ~50 completions inside the 1 s window (timing slack for 1-core CI)
    assert 25 <= res.n_ok <= 60
    assert 25.0 <= s["p50_ms"] <= 150.0


def test_open_loop_sheds_beyond_max_inflight(loop):
    server, url, _ = serve_with_delay(loop, 0.5)
    res = loop.run_until_complete(
        run_load_open(url, b"x", "application/octet-stream",
                      rate_per_s=100.0, duration_s=0.5, warmup_s=0.0,
                      max_inflight=2))
    loop.run_until_complete(server.close())
    assert res.n_err > 10  # client-side shed is reported, not hidden
    assert res.n_ok == 0  # nothing completes inside a 0.5 s window


def test_errors_counted(loop):
    async def handler(request):
        return web.Response(status=500)

    app = web.Application()
    app.router.add_post("/v1/models/m:predict", handler)
    server = TestServer(app)
    loop.run_until_complete(server.start_server())
    url = f"http://{server.host}:{server.port}/v1/models/m:predict"
    res = loop.run_until_complete(
        run_load(url, b"x", "application/octet-stream",
                 duration_s=0.3, concurrency=2, warmup_s=0.0))
    loop.run_until_complete(server.close())
    assert res.n_ok == 0 and res.n_err > 0


def test_items_per_request_scales_throughput():
    from tpuserve.bench.loadgen import LoadResult

    r = LoadResult(mode="closed", n_ok=10, duration_s=2.0, items_per_request=8)
    assert r.throughput == 40.0
    assert r.summary()["items_per_request"] == 8
    assert "items_per_request" not in LoadResult(n_ok=1, duration_s=1.0).summary()


def test_synthetic_batch_payload_shape():
    import io

    import numpy as np

    from tpuserve.bench.loadgen import synthetic_image_npy_batch

    arr = np.load(io.BytesIO(synthetic_image_npy_batch(16, 4)), allow_pickle=False)
    assert arr.shape == (4, 16, 16, 3) and arr.dtype == np.uint8


def test_synthetic_pool_distinct_bodies():
    """Miss-only workload construction: every pooled payload is distinct
    (distinct pixels => distinct cache keys) and decodes to the wire shape."""
    import io

    import numpy as np

    from tpuserve.bench.loadgen import synthetic_pool

    pool = synthetic_pool("npy", 8, edge=8)
    assert len(pool) == 8
    assert len({p for p in pool}) == 8  # all byte-distinct
    arr = np.load(io.BytesIO(pool[0]))
    assert arr.shape == (8, 8, 3) and arr.dtype == np.uint8
    batched = synthetic_pool("npy", 3, edge=8, batch=4)
    assert np.load(io.BytesIO(batched[0])).shape == (4, 8, 8, 3)


def test_synthetic_prompt_pool_mixed_lengths():
    """Generative workload construction (ISSUE 9): every pooled body is a
    distinct (prompt, seed) pair — the cache-key contract guarantees no
    aliasing — and textgen pools spread max_new_tokens across the range so
    the offered load has MIXED output lengths (the engine's early-exit
    counters only move on mixed lengths)."""
    import json

    import pytest

    from tpuserve.bench.loadgen import synthetic_prompt_pool

    pool = synthetic_prompt_pool(16, max_new=(2, 24))
    bodies = [json.loads(p) for p in pool]
    assert len({b["seed"] for b in bodies}) == 16  # no key can alias
    lens = [b["max_new_tokens"] for b in bodies]
    assert min(lens) >= 2 and max(lens) <= 24
    assert len(set(lens)) > 4  # genuinely mixed, not constant
    sd = [json.loads(p) for p in synthetic_prompt_pool(4, sd=True)]
    assert all("max_new_tokens" not in b for b in sd)  # fixed-steps txt2img
    with pytest.raises(ValueError, match="max_new"):
        synthetic_prompt_pool(4, max_new=(5, 2))


def test_closed_loop_cycles_distinct_pool(loop):
    """A list payload round-robins across workers and is reported in the
    summary, so a bench JSON always shows the workload shape."""
    seen = []

    async def handler(request: web.Request) -> web.Response:
        seen.append(await request.read())
        return web.json_response({"ok": True})

    async def go():
        app = web.Application()
        app.router.add_post("/v1/x", handler)
        server = TestServer(app)
        await server.start_server()
        pool = [f"payload-{i}".encode() for i in range(4)]
        res = await run_load(f"http://127.0.0.1:{server.port}/v1/x", pool,
                             "application/octet-stream", duration_s=0.4,
                             concurrency=4, warmup_s=0.0)
        await server.close()
        assert res.n_ok > 0
        assert res.summary()["distinct_payloads"] == 4
        # All four bodies actually hit the wire.
        assert {s.decode() for s in seen} == {f"payload-{i}" for i in range(4)}

    loop.run_until_complete(go())


def test_closed_loop_concurrency_scales_with_chips():
    """ISSUE 7 satellite: loadgen connection count derives from the chip
    count — an 8-chip mesh driven with a single-chip connection count is
    demand-starved and the bench under-reports by design."""
    from tpuserve.bench.loadgen import closed_loop_concurrency

    # Single chip: identical to the historical formula min(384, max(32, 3*top)).
    assert closed_loop_concurrency([8, 32], 1) == 96
    assert closed_loop_concurrency([128], 1) == 384  # per-chip cap
    assert closed_loop_concurrency([1, 2], 1) == 32  # floor
    # 8 chips: 8x the demand, cap scales too.
    assert closed_loop_concurrency([8, 32], 8) == 8 * 96
    assert closed_loop_concurrency([128], 8) == 3 * 128 * 8
    assert closed_loop_concurrency([128], 8) <= 384 * 8
    # Degenerate inputs stay sane.
    assert closed_loop_concurrency([], 0) == 32
