"""Generation on the mesh (ISSUE 20): sharded==single token parity over
mixed lengths/seeds/temps with paged KV on, balanced replica-per-chip
placement under sustained mixed load, zero recompiles across churn AND
publish/rollback on both legs, the group's fanned staged canary, the MoE
textgen variant, and the fleet scheduler's chip-budget placement by
parallelism degree. docs/PERFORMANCE.md "Generation on the mesh"."""

import asyncio
import json
import time

import pytest

from tpuserve.config import (GenserveConfig, ModelConfig, ParallelConfig,
                             SchedulerConfig, ServerConfig)
from tpuserve.genserve import GenEngine, GenEngineGroup
from tpuserve.models import build
from tpuserve.obs import SCHED_SHED_REASONS, Metrics
from tpuserve.runtime import build_runtime
from tpuserve.scheduler import FleetScheduler

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning")

TG_OPTS = dict(layers=1, d_model=32, heads=2, d_ff=64, vocab_size=512,
               prompt_len=16, max_new_tokens=64)

# Mixed lengths / seeds / max_new / temperatures — greedy and sampled lanes
# both cross the sharded gumbel draw (the jax_threefry_partitionable seam).
PROMPTS = [
    ("a", 1, 3, 0.0),
    ("the quick brown fox jumps over the lazy dog again and again", 2,
     12, 0.7),
    ("short prompt", 3, 1, 0.0),
    ("one two three four five six seven eight nine ten eleven twelve "
     "thirteen fourteen fifteen sixteen", 4, 8, 0.3),
    ("hello", 5, 20, 1.0),
    ("mid size prompt with a few words", 6, 5, 0.0),
]


def tg_cfg(**over) -> ModelConfig:
    base = dict(name="tg", family="textgen", batch_buckets=[1, 2, 4],
                dtype="float32", parallelism="single", max_queue=64,
                request_timeout_ms=60_000.0, options=dict(TG_OPTS))
    base.update(over)
    return ModelConfig(**base)


def paged() -> GenserveConfig:
    return GenserveConfig(slots=4, kv_paging=True, kv_page_tokens=8)


@pytest.fixture(scope="module")
def single_rt():
    """Single-mesh paged baseline — the parity reference."""
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    GenEngine(model, rt, Metrics(), paged()).compile()
    return model, rt


@pytest.fixture(scope="module")
def sharded_rt():
    """Tensor-parallel decode: tp=2 over 4 of the conftest's 8 forced host
    devices (data=2 x model=2 mesh). Same deterministic params as
    single_rt — build() seeds from the model config, not the mesh."""
    model = build(tg_cfg(parallelism="sharded", tp=2))
    rt = build_runtime(model, compile_forward=False,
                       parallel=ParallelConfig(n_chips=4))
    GenEngine(model, rt, Metrics(), paged()).compile()
    return model, rt


@pytest.fixture(scope="module")
def replica_rt():
    """Replica-per-chip runtime: 4 independent 1-device meshes."""
    model = build(tg_cfg(parallelism="replica"))
    rt = build_runtime(model, compile_forward=False,
                       parallel=ParallelConfig(n_chips=4))
    met = Metrics()
    GenEngineGroup(model, rt, met, paged()).compile()
    return model, rt, met


def prompt_item(model, prompt="hello world", seed=0, max_new=8, temp=0.0):
    body = {"prompt": prompt, "seed": seed, "max_new_tokens": max_new}
    if temp:
        body["temperature"] = temp
    return model.host_decode(json.dumps(body).encode(), "application/json")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def drive(eng, model, prompts):
    await eng.start()
    futs = [eng.submit(prompt_item(model, p, seed=s, max_new=n, temp=t))
            for (p, s, n, t) in prompts]
    res = await asyncio.gather(*futs)
    await eng.stop()
    return [r["tokens"] for r in res]


# ---------------------------------------------------------------------------
# Sharded decode: token parity and the zero-recompile obligation
# ---------------------------------------------------------------------------

def test_sharded_signature_and_geometry(sharded_rt, replica_rt):
    _, sh = sharded_rt
    _, rp, _ = replica_rt
    assert sh.parallel_signature == "sharded@d2"
    assert sh.n_chips == 4 and sh.n_replicas == 1
    assert rp.parallel_signature == "replica@4"
    assert rp.n_chips == 4 and rp.n_replicas == 4


def test_sharded_matches_single_token_identical(single_rt, sharded_rt):
    """Tensor-parallel decode must be byte-identical to the single mesh at
    the same seed/temperature with paged KV on — head-sharded attention
    changes the reduction LAYOUT, never the reduction, and
    jax_threefry_partitionable makes the sampled lanes sharding-invariant
    too (the gumbel draw over vocab-sharded logits draws the SAME bits it
    would on one device)."""
    s_model, _ = single_rt
    sh_model, _ = sharded_rt
    s_eng, _ = _make_engine(single_rt)
    sh_eng, _ = _make_engine(sharded_rt)
    base = run(drive(s_eng, s_model, PROMPTS))
    mesh = run(drive(sh_eng, sh_model, PROMPTS))
    assert base == mesh, (base, mesh)
    # Both page ledgers balanced after the drain.
    assert sh_eng.pages.n_free == sh_eng.pages.usable
    assert sh_eng.pages.n_reserved == 0


def _make_engine(fix, metrics=None):
    model, rt = fix[0], fix[1]
    m = metrics or Metrics()
    eng = GenEngine(model, rt, m, paged())
    eng.compile()  # reuses the runtime's registered programs
    return eng, m


def test_sharded_zero_recompiles_across_churn_and_reload(sharded_rt):
    """Slot churn + page churn + a publish AND a rollback mid-churn with
    runtime_compiles_total delta exactly 0 on the sharded leg: page rows,
    block tables, and slot indices are traced arguments of the ONE
    per-mesh step executable."""
    model, rt = sharded_rt
    eng, _ = _make_engine(sharded_rt)
    c0 = rt.compiles_total

    async def churn():
        await eng.start()
        futs = [eng.submit(prompt_item(model, f"wave one {i}", seed=i,
                                       max_new=4 + i % 5))
                for i in range(8)]
        await asyncio.gather(*futs)
        rt.publish(rt.stage_params())
        futs = [eng.submit(prompt_item(model, f"wave two {i}", seed=10 + i,
                                       max_new=3 + i % 7, temp=0.5))
                for i in range(8)]
        await asyncio.gather(*futs)
        rt.rollback()
        futs = [eng.submit(prompt_item(model, f"wave three {i}", seed=20 + i,
                                       max_new=6))
                for i in range(4)]
        await asyncio.gather(*futs)
        await eng.stop()

    run(churn())
    assert rt.compiles_total == c0
    assert eng.arena.n_free == eng.slots
    assert eng.pages.n_free == eng.pages.usable


# ---------------------------------------------------------------------------
# Replica-per-chip group: balance, parity, canary fan-out, zero recompiles
# ---------------------------------------------------------------------------

def test_replica_group_balanced_under_sustained_mixed_load(replica_rt):
    """Least-loaded placement keeps every chip generating: under a
    sustained mixed-length load every replica's steps/units counters are
    nonzero — both on the /stats per_replica rows and on the
    gen_replica_*_total metric rows the placement-balance alert reads."""
    model, rt, met = replica_rt
    grp = GenEngineGroup(model, rt, met, paged())
    grp.compile()
    assert len(grp.engines) == 4 and grp.slots == 16
    c0 = rt.compiles_total

    async def load():
        await grp.start()
        futs = [grp.submit(prompt_item(model, f"prompt number {i}",
                                       seed=i, max_new=4 + i % 9,
                                       temp=(0.0, 0.4, 0.9)[i % 3]))
                for i in range(24)]
        res = await asyncio.gather(*futs)
        ok = await grp.drain(asyncio.get_running_loop().time() + 10)
        await grp.stop()
        return res, ok

    res, ok = run(load())
    assert ok and len(res) == 24
    stats = grp.pipeline_stats()
    rows = stats["per_replica"]
    assert [r["replica"] for r in rows] == [0, 1, 2, 3]
    assert all(r["steps_total"] > 0 for r in rows), rows
    assert all(r["units_total"] > 0 for r in rows), rows
    assert all(r["kv"]["free"] == r["kv"]["usable"] for r in rows), rows
    # The metric rows are the same truth (prebound singletons).
    for i in range(4):
        assert met.counter(
            f"gen_replica_steps_total{{model=tg,replica={i}}}").value > 0
        assert met.counter(
            f"gen_replica_units_total{{model=tg,replica={i}}}").value > 0
    # Units conserve: per-replica rows decompose the model-level total.
    assert sum(r["units_total"] for r in rows) == sum(
        met.counter(
            f"gen_replica_units_total{{model=tg,replica={i}}}").value
        for i in range(4))
    # Zero recompiles across the whole run — the group reused the
    # registered per-mesh executables.
    assert rt.compiles_total == c0


def test_replica_group_parity_and_canary_fanout(single_rt, replica_rt):
    """A replica engine runs the SAME single-mesh program — tokens match
    the single baseline exactly; the group's staged canary fans to every
    replica and a failure names the replica that rejected."""
    s_model, _ = single_rt
    model, rt, met = replica_rt
    grp = GenEngineGroup(model, rt, met, paged())
    grp.compile()
    s_eng, _ = _make_engine(single_rt)
    sub = PROMPTS[:3]
    base = run(drive(s_eng, s_model, sub))
    mesh = run(drive(grp, model, sub))
    assert base == mesh, (base, mesh)

    # Canary fan-out: a clean staged tree passes on all four replicas with
    # zero recompiles (params_override is a traced donor, not a geometry).
    c0 = rt.compiles_total
    grp2 = GenEngineGroup(model, rt, met, paged())
    grp2.compile()
    grp2.staged_canary_sync(rt.stage_params())
    assert rt.compiles_total == c0
    # A broken candidate (wrong tree structure — a truncated checkpoint)
    # rejects and the error names the replica that refused it.
    with pytest.raises(ValueError, match=r"staged canary failed on "
                                         r"replica 0"):
        grp2.staged_canary_sync({"not": "a-param-tree"})


def test_replica_group_zero_recompiles_across_reload(replica_rt):
    """publish + rollback mid-load on the group: compiles delta exactly 0
    — every replica flips the same versioned param slot."""
    model, rt, met = replica_rt
    grp = GenEngineGroup(model, rt, met, paged())
    grp.compile()
    c0 = rt.compiles_total

    async def churn():
        await grp.start()
        futs = [grp.submit(prompt_item(model, f"pre {i}", seed=i, max_new=5))
                for i in range(8)]
        await asyncio.gather(*futs)
        rt.publish(rt.stage_params())
        futs = [grp.submit(prompt_item(model, f"post {i}", seed=i,
                                       max_new=5, temp=0.6))
                for i in range(8)]
        await asyncio.gather(*futs)
        rt.rollback()
        futs = [grp.submit(prompt_item(model, f"back {i}", seed=i, max_new=4))
                for i in range(4)]
        await asyncio.gather(*futs)
        await grp.stop()

    run(churn())
    assert rt.compiles_total == c0


# ---------------------------------------------------------------------------
# MoE textgen variant
# ---------------------------------------------------------------------------

def test_moe_textgen_engine_decode():
    """options.moe_experts swaps the dense MLP for a top-1 Switch FFN over
    ops.moe.switch_route — same engine, same programs, deterministic."""
    model = build(tg_cfg(options=dict(TG_OPTS, moe_experts=4)))
    rt = build_runtime(model, compile_forward=False)
    eng = GenEngine(model, rt, Metrics(), paged())
    eng.compile()
    toks = run(drive(eng, model, PROMPTS[:2]))
    assert all(len(t) > 0 for t in toks)
    again = GenEngine(model, rt, Metrics(), paged())
    again.compile()
    assert toks == run(drive(again, model, PROMPTS[:2]))


def test_moe_experts_validation():
    for bad in (1, -2):
        with pytest.raises(ValueError, match="moe_experts"):
            build(tg_cfg(options=dict(TG_OPTS, moe_experts=bad)))


# ---------------------------------------------------------------------------
# Fleet scheduler: chip-budget placement by parallelism degree
# ---------------------------------------------------------------------------

class FakeRuntime:
    def __init__(self, n_chips=1, signature="single"):
        self.n_chips = n_chips
        self.parallel_signature = signature
        self.released = 0

    def release_params(self):
        self.released += 1


class StubBatcher:
    def __init__(self, pending=0):
        self.pending = pending
        self.device_time_cb = None

    def estimate_clear_s(self):
        return None

    def predicted_service_s(self, n_items=1):
        return None


def model_cfg(name, **over):
    base = dict(family="toy", batch_buckets=[1], deadline_ms=5.0,
                dtype="float32", num_classes=10, parallelism="single",
                request_timeout_ms=10_000.0, wire_size=8)
    base.update(over)
    return ModelConfig(name=name, **base)


def make_sched(**cfg_over) -> FleetScheduler:
    base = dict(enabled=True)
    base.update(cfg_over)
    return FleetScheduler(SchedulerConfig(**base), Metrics())


async def noop_warm():
    return {"version": 1}


def test_chip_budget_config_validation():
    with pytest.raises(ValueError):
        SchedulerConfig(chip_budget=-1)
    assert "chip_budget" in SCHED_SHED_REASONS


def test_chip_budget_degrees_and_stats():
    """Placement is by parallelism DEGREE: a replica@4 group claims 4
    chips, a single-chip model 1, and /stats surfaces both the per-model
    parallel block and the fleet occupancy."""
    sched = make_sched(chip_budget=8)
    sched.register("wide", StubBatcher(),
                   model_cfg("wide", parallelism="replica"),
                   runtime=FakeRuntime(4, "replica@4"))
    sched.register("narrow", StubBatcher(), model_cfg("narrow"),
                   runtime=FakeRuntime(1, "single"))
    sched.register("bare", StubBatcher(), model_cfg("bare"))  # no runtime
    assert sched.chips_in_use() == 6
    s = sched.stats()
    assert s["chip_budget"] == 8 and s["chips_in_use"] == 6
    assert s["models"]["wide"]["parallel"] == {
        "signature": "replica@4", "degree": 4}
    assert s["models"]["bare"]["parallel"] == {
        "signature": "single", "degree": 1}


def test_chip_budget_sheds_cold_model_that_cannot_fit(loop):
    """A cold model whose degree overflows the budget sheds 503
    chip_budget at admission (warm residents are not victims unless they
    are idle cold_start models), and the :warm endpoint refuses with the
    same accounting."""
    async def go():
        sched = make_sched(chip_budget=4)
        sched.register("resident", StubBatcher(),
                       model_cfg("resident", parallelism="replica"),
                       runtime=FakeRuntime(4, "replica@4"))
        sched.register("cold2", StubBatcher(),
                       model_cfg("cold2", cold_start=True),
                       runtime=FakeRuntime(2, "sharded@d1"),
                       warm_fn=noop_warm, cold=True)
        shed = sched.check_admission("cold2", "interactive")
        assert shed is not None and shed.status == 503
        assert shed.reason == "chip_budget"
        assert "needs 2 chip(s)" in shed.message
        assert sched.state_of("cold2") == "cold"  # warm-up never kicked
        assert sched._entries["cold2"].shed_counters[
            "chip_budget"].value == 1
        with pytest.raises(ValueError, match="chip budget"):
            await sched.warm("cold2")

    loop.run_until_complete(go())


def test_chip_budget_demotes_idle_cold_start_to_make_room(loop):
    """An idle warm cold_start model is demoted (largest degree first) to
    make room for an incoming cold model — placement prefers serving the
    model with demand over holding idle params resident."""
    async def go():
        sched = make_sched(chip_budget=4)
        idle_rt = FakeRuntime(4, "replica@4")
        sched.register("idle", StubBatcher(pending=0),
                       model_cfg("idle", parallelism="replica",
                                 cold_start=True),
                       runtime=idle_rt)
        sched.register("cold2", StubBatcher(),
                       model_cfg("cold2", cold_start=True),
                       runtime=FakeRuntime(2, "sharded@d1"),
                       warm_fn=noop_warm, cold=True)
        assert sched.chips_in_use() == 4
        shed = sched.check_admission("cold2", "interactive")
        # The victim was demoted and the warm-up kicked: the caller sees
        # the ordinary model_warming shed, not chip_budget.
        assert shed is not None and shed.reason == "model_warming"
        assert sched.state_of("idle") == "cold"
        assert idle_rt.released == 1
        info = await sched.warm("cold2")
        assert info["state"] == "warm"
        assert sched.chips_in_use() == 2

    loop.run_until_complete(go())


def test_chip_budget_busy_resident_is_not_a_victim(loop):
    """A cold_start resident with queued work is never demoted — the
    budget sheds the newcomer instead of thrashing a loaded model."""
    async def go():
        sched = make_sched(chip_budget=4)
        sched.register("busy", StubBatcher(pending=3),
                       model_cfg("busy", parallelism="replica",
                                 cold_start=True),
                       runtime=FakeRuntime(4, "replica@4"))
        sched.register("cold1", StubBatcher(),
                       model_cfg("cold1", cold_start=True),
                       runtime=FakeRuntime(1, "single"),
                       warm_fn=noop_warm, cold=True)
        shed = sched.check_admission("cold1", "interactive")
        assert shed is not None and shed.reason == "chip_budget"
        assert sched.state_of("busy") == "warm"

    loop.run_until_complete(go())


def test_chip_budget_zero_is_unlimited(loop):
    async def go():
        sched = make_sched(chip_budget=0)
        sched.register("wide", StubBatcher(),
                       model_cfg("wide", parallelism="replica"),
                       runtime=FakeRuntime(8, "replica@8"))
        sched.register("cold", StubBatcher(),
                       model_cfg("cold", cold_start=True),
                       runtime=FakeRuntime(8, "replica@8"),
                       warm_fn=noop_warm, cold=True)
        shed = sched.check_admission("cold", "interactive")
        assert shed is not None and shed.reason == "model_warming"
        await sched.warm("cold")  # let the kicked warm task finish

    loop.run_until_complete(go())


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# ---------------------------------------------------------------------------
# HTTP: the server builds a group for replica runtimes; /stats shows rows
# ---------------------------------------------------------------------------

def test_http_replica_group_stats_and_metrics():
    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(
        decode_threads=2,
        genserve=GenserveConfig(enabled=True, slots=2, kv_paging=True,
                                kv_page_tokens=8),
        parallel=ParallelConfig(mode="replica", n_chips=2),
        models=[tg_cfg()])
    state = ServerState(cfg)
    state.build()
    assert isinstance(state.engines["tg"], GenEngineGroup)

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            for i in range(6):
                r = await client.post(
                    "/v1/models/tg:generate",
                    data=json.dumps({"prompt": f"hello mesh {i}", "seed": i,
                                     "max_new_tokens": 5}),
                    headers={"Content-Type": "application/json"})
                assert r.status == 200, await r.text()
            stats = await (await client.get("/stats")).json()
            gs = stats["genserve"]["tg"]
            assert gs["replicas"] == 2 and gs["slots"] == 4
            rows = gs["per_replica"]
            assert [r["replica"] for r in rows] == [0, 1]
            assert all(r["steps_total"] > 0 for r in rows), rows
            assert all("kv" in r for r in rows)
            metrics = await (await client.get("/metrics")).text()
            assert 'gen_replica_steps_total{model="tg",replica="0"}' \
                in metrics
            assert 'gen_replica_steps_total{model="tg",replica="1"}' \
                in metrics
            assert 'gen_replica_kv_pages_free{model="tg",replica="0"}' \
                in metrics
        finally:
            await client.close()

    run(go())
