"""Framed binary wire format (ISSUE 11): zero-copy parse, hardening (every
malformed body a machine-readable 400, never a 500), byte-identical answers
vs the npy path, arena decode-into equivalence, and cache-key coverage of
the new content type."""

import asyncio
import io

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve import frame, preproc
from tpuserve.cache import ModelCache, item_digest
from tpuserve.config import CacheConfig, ModelConfig, ServerConfig
from tpuserve.models import build
from tpuserve.server import ServerState, make_app

EDGE = 8  # toy wire edge


def rgb_items(n, edge=EDGE, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 255, (edge, edge, 3), dtype=np.uint8)
            for _ in range(n)]


def npy_batch_bytes(items):
    buf = io.BytesIO()
    np.save(buf, np.stack(items))
    return buf.getvalue()


# -- parse/encode roundtrip ---------------------------------------------------

def test_roundtrip_rgb8_zero_copy():
    items = rgb_items(3)
    body = frame.encode_frame(items, frame.KIND_RGB8, EDGE)
    assert len(body) == frame.frame_nbytes(frame.KIND_RGB8, EDGE, 3)
    out = frame.parse_frame(body, kind=frame.KIND_RGB8, edge=EDGE,
                            max_items=64)
    assert len(out) == 3
    for a, b in zip(items, out):
        np.testing.assert_array_equal(a, b)
        # Zero-copy contract: parsed items are read-only views over the
        # body, not per-item allocations — the one copy is assemble_into's.
        assert not b.flags.writeable
        assert not b.flags.owndata


def test_roundtrip_yuv420_matches_npy_conversion():
    """A yuv420 frame built from rgb_to_yuv420 planes decodes to EXACTLY
    the items the npy path produces for the same pixels — the precondition
    for byte-identical HTTP answers across the two wires."""
    edge = 16
    rgbs = rgb_items(2, edge=edge, seed=3)
    planes = [preproc.rgb_to_yuv420(r) for r in rgbs]
    body = frame.encode_frame(planes, frame.KIND_YUV420, edge)
    assert len(body) == frame.frame_nbytes(frame.KIND_YUV420, edge, 2)
    out = frame.parse_frame(body, kind=frame.KIND_YUV420, edge=edge,
                            max_items=64)
    for (y, u, v), (py, pu, pv) in zip(out, planes):
        np.testing.assert_array_equal(y, py)
        np.testing.assert_array_equal(u, pu)
        np.testing.assert_array_equal(v, pv)
        assert y.shape == (edge, edge) and u.shape == (edge // 2, edge // 2)
        assert not y.flags.writeable


def test_item_nbytes():
    assert frame.item_nbytes(frame.KIND_RGB8, 16) == 768
    assert frame.item_nbytes(frame.KIND_YUV420, 16) == 256 + 2 * 64  # 1.5 B/px


# -- hardening: every malformed body is a FrameError (-> 400) -----------------

def good_frame(n=2):
    return frame.encode_frame(rgb_items(n), frame.KIND_RGB8, EDGE)


def parse(body, **kw):
    args = dict(kind=frame.KIND_RGB8, edge=EDGE, max_items=16)
    args.update(kw)
    return frame.parse_frame(body, **args)


@pytest.mark.parametrize("body,fragment", [
    (b"", "truncated header"),
    (b"TPUF\x01\x00", "truncated header"),
    (b"NOPE" + good_frame()[4:], "bad magic"),
    (good_frame()[:16][:4] + b"\x63\x00" + good_frame()[6:], "version"),
])
def test_header_hardening(body, fragment):
    with pytest.raises(frame.FrameError, match=fragment):
        parse(body)


def test_truncated_offset_table():
    with pytest.raises(frame.FrameError, match="truncated offset table"):
        parse(good_frame(2)[:frame.HEADER_SIZE + 4])


def test_offsets_past_end_of_body():
    body = good_frame(2)
    with pytest.raises(frame.FrameError, match="payload region"):
        parse(body[:-10])  # table intact, payload truncated


def test_trailing_garbage_rejected():
    with pytest.raises(frame.FrameError, match="payload region"):
        parse(good_frame(2) + b"xx")


def test_count_over_max_items():
    body = good_frame(4)
    with pytest.raises(frame.FrameError, match="per-request limit"):
        parse(body, max_items=3)


def test_zero_count():
    import struct
    hdr = struct.pack("<4sHHII", b"TPUF", 1, frame.KIND_RGB8, 0, EDGE)
    with pytest.raises(frame.FrameError, match="count"):
        parse(hdr + np.asarray([0], "<u8").tobytes())


def test_zero_length_item():
    """An offset table with a repeated offset (zero-length item) rejects —
    the wire carries fixed-size items only."""
    import struct
    size = frame.item_nbytes(frame.KIND_RGB8, EDGE)
    hdr = struct.pack("<4sHHII", b"TPUF", 1, frame.KIND_RGB8, 2, EDGE)
    table = np.asarray([0, 0, size], "<u8").tobytes()  # item 0 empty
    payload = bytes(size)
    with pytest.raises(frame.FrameError, match="zero-length"):
        parse(hdr + table + payload)


def test_non_ascending_offsets():
    import struct
    size = frame.item_nbytes(frame.KIND_RGB8, EDGE)
    hdr = struct.pack("<4sHHII", b"TPUF", 1, frame.KIND_RGB8, 2, EDGE)
    table = np.asarray([0, 2 * size, 2 * size], "<u8").tobytes()
    with pytest.raises(frame.FrameError):
        parse(hdr + table + bytes(2 * size))


def test_kind_and_edge_mismatch():
    planes = [preproc.rgb_to_yuv420(rgb_items(1, edge=16)[0])]
    yuv = frame.encode_frame(planes, frame.KIND_YUV420, 16)
    with pytest.raises(frame.FrameError, match="wire_format"):
        frame.parse_frame(yuv, kind=frame.KIND_RGB8, edge=16, max_items=4)
    with pytest.raises(frame.FrameError, match="wire_size"):
        parse(good_frame(1), edge=16)


def test_garbage_planes_wrong_size():
    """A frame whose payload bytes do not partition into exact items
    (garbage planes) rejects instead of mis-slicing."""
    body = good_frame(2)
    # Corrupt the LAST table entry so the item spans are wrong.
    import struct
    size = frame.item_nbytes(frame.KIND_RGB8, EDGE)
    hdr = struct.pack("<4sHHII", b"TPUF", 1, frame.KIND_RGB8, 2, EDGE)
    table = np.asarray([0, size - 7, 2 * size], "<u8").tobytes()
    with pytest.raises(frame.FrameError, match="expected"):
        parse(hdr + table + body[frame.HEADER_SIZE + 24:])


# -- model decode + arena decode-into seam ------------------------------------

def test_toy_host_decode_items_frame():
    cfg = ModelConfig(name="toy", family="toy", dtype="float32",
                      num_classes=10, parallelism="single")
    model = build(cfg)
    items = rgb_items(3)
    got, batched = model.host_decode_items(
        frame.encode_frame(items, frame.KIND_RGB8, EDGE), frame.CONTENT_TYPE)
    assert batched and len(got) == 3
    for a, b in zip(items, got):
        np.testing.assert_array_equal(a, b)


def test_assemble_into_accepts_readonly_frame_views():
    """The decode-into seam: zero-copy (read-only) frame views copy
    straight into a preallocated arena-shaped buffer, producing exactly
    what the allocating assemble would."""
    cfg = ModelConfig(name="toy", family="toy", dtype="float32",
                      num_classes=10, parallelism="single",
                      batch_buckets=[4])
    model = build(cfg)
    items = model.host_decode_items(
        frame.encode_frame(rgb_items(3), frame.KIND_RGB8, EDGE),
        frame.CONTENT_TYPE)[0]
    bucket = (4,)
    sig = model.input_signature(bucket)
    out = np.ones(tuple(sig.shape), sig.dtype)  # dirty: padding must zero
    got = model.assemble_into(items, bucket, out)
    np.testing.assert_array_equal(got, model.assemble(items, bucket))
    assert got is out  # in place, no allocation


def test_vision_yuv420_frame_decode_equals_npy_path():
    """For the same pixels, the framed yuv420 wire and the npy wire hand
    the batcher IDENTICAL decoded items (so responses are byte-identical
    downstream — the HTTP twin is pinned on toy below)."""
    cfg = ModelConfig(name="m", family="mobilenetv3", dtype="float32",
                      wire_size=16, wire_format="yuv420",
                      parallelism="single")
    model = build(cfg)
    rgbs = rgb_items(2, edge=16, seed=9)
    npy_items, _ = model.host_decode_items(
        npy_batch_bytes(rgbs), "application/x-npy")
    planes = [preproc.rgb_to_yuv420(r) for r in rgbs]
    frame_items, batched = model.host_decode_items(
        frame.encode_frame(planes, frame.KIND_YUV420, 16),
        frame.CONTENT_TYPE)
    assert batched
    for (a1, a2, a3), (b1, b2, b3) in zip(npy_items, frame_items):
        np.testing.assert_array_equal(a1, b1)
        np.testing.assert_array_equal(a2, b2)
        np.testing.assert_array_equal(a3, b3)


# -- cache keys cover the content type ----------------------------------------

def test_router_tier_cache_key_covers_frame_content_type():
    """The router tier keys its wire cache on (verb, content type, body)
    — the new content type MUST split keys even for equal body bytes, and
    equal pixels on different wires must never alias."""
    body = good_frame(1)
    assert item_digest(("predict", frame.CONTENT_TYPE, body)) != \
        item_digest(("predict", "application/x-npy", body))
    cache = ModelCache("m", CacheConfig(enabled=True), __import__(
        "tpuserve.obs", fromlist=["Metrics"]).Metrics(), version_fn=lambda: 1)
    k1 = cache.key_for(("predict", frame.CONTENT_TYPE, body))
    k2 = cache.key_for(("predict", "application/x-npy", body))
    assert k1 != k2


# -- HTTP: byte-identical answers, 400-never-500 ------------------------------

@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def client(loop):
    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single",
                            request_timeout_ms=10_000.0)],
        decode_threads=2,
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def setup():
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    client = loop.run_until_complete(setup())
    yield lambda coro: loop.run_until_complete(coro), client, state
    loop.run_until_complete(client.close())


def test_http_frame_byte_identical_to_npy(client):
    run, c, state = client
    items = rgb_items(3, seed=17)

    async def go():
        r1 = await c.post("/v1/models/toy:classify",
                          data=frame.encode_frame(items, frame.KIND_RGB8,
                                                  EDGE),
                          headers={"Content-Type": frame.CONTENT_TYPE})
        b1 = await r1.read()
        r2 = await c.post("/v1/models/toy:classify",
                          data=npy_batch_bytes(items),
                          headers={"Content-Type": "application/x-npy"})
        b2 = await r2.read()
        return r1.status, b1, r2.status, b2

    s1, b1, s2, b2 = run(go())
    assert s1 == 200 and s2 == 200
    assert b1 == b2  # byte-identical across the two wires


def test_http_malformed_frames_400_never_500(client):
    run, c, state = client
    bad_bodies = [
        b"",                       # truncated header
        b"TPUF\x01\x00",           # short
        b"NOPE" + good_frame()[4:],  # bad magic
        good_frame(2)[:-10],       # table past end of body
        good_frame(2) + b"junk",   # trailing garbage
    ]

    async def go():
        outs = []
        for body in bad_bodies:
            r = await c.post("/v1/models/toy:classify", data=body,
                             headers={"Content-Type": frame.CONTENT_TYPE})
            outs.append((r.status, await r.json()))
        # The server survives every malformed frame: a good one still 200s.
        ok = await c.post("/v1/models/toy:classify", data=good_frame(1),
                          headers={"Content-Type": frame.CONTENT_TYPE})
        return outs, ok.status

    outs, ok_status = run(go())
    for status, payload in outs:
        assert status == 400, (status, payload)  # never 500
        assert payload["error"].startswith("frame:"), payload
    assert ok_status == 200
    # Every malformed body ticked the dedicated frame-error counter (and
    # the /stats ingest block exposes it).
    assert state.handles["toy"].frame_errors.value == len(bad_bodies)


def test_http_frame_over_max_items_400(client):
    run, c, state = client

    async def go():
        body = frame.encode_frame(rgb_items(2), frame.KIND_RGB8, EDGE)
        # Patch the count field to an absurd value: the table check fires
        # before any allocation proportional to the claimed count... the
        # parse must reject, not 500.
        import struct
        big = struct.pack("<4sHHII", b"TPUF", 1, frame.KIND_RGB8,
                          5000, EDGE) + body[frame.HEADER_SIZE:]
        r = await c.post("/v1/models/toy:classify", data=big,
                         headers={"Content-Type": frame.CONTENT_TYPE})
        return r.status, await r.json()

    status, payload = run(go())
    assert status == 400
    assert "limit" in payload["error"]


def test_http_ingest_phases_observed(client):
    """body_read/parse join the per-phase attribution: after traffic, the
    request-scoped ingest histograms have samples in /stats."""
    run, c, state = client
    summary = state.metrics.summary()["latency"]
    for phase in ("body_read", "parse"):
        row = summary.get(f"latency_ms{{model=toy,phase={phase}}}")
        assert row is not None and row["n"] > 0, (phase, row)
