"""Preprocessing (C3/C12): YUV420 wire-format parity vs the RGB path, native
shim decode + fallbacks. VERDICT.md r2 item 5 (the r2 parity check lived only
in the judge's verdict; this pins it in-repo)."""

import io

import numpy as np
import pytest

from tpuserve import native, preproc


def photo_jpeg(edge=256, quality=90) -> bytes:
    from PIL import Image

    rng = np.random.default_rng(7)
    y, x = np.mgrid[0:edge, 0:edge].astype(np.float32) / edge
    arr = np.stack([
        0.5 + 0.4 * np.sin(6.0 * x), 0.5 + 0.4 * np.cos(5.0 * y),
        0.5 + 0.4 * np.sin(4.0 * (x + y)),
    ], axis=-1)
    arr = np.clip((arr + rng.normal(0, 0.03, arr.shape)) * 255, 0, 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def test_yuv420_vs_rgb_parity_on_device():
    """Same JPEG through both wire formats -> same normalized tensor (<=0.03,
    the bound the r2 judge measured at 0.021)."""
    payload = photo_jpeg()
    rgb = preproc.decode_image(payload, "image/jpeg", edge=256)
    y, u, v = preproc.decode_image_yuv420(payload, "image/jpeg", 256)

    via_rgb = np.asarray(preproc.device_prepare_images(
        rgb[None], 224, dtype=np.float32))
    via_yuv = np.asarray(preproc.device_prepare_images_yuv420(
        y[None], u[None], v[None], 224, dtype=np.float32))
    # Undo ImageNet normalization to compare in [0,1] pixel units.
    std = np.asarray(preproc.IMAGENET_STD, np.float32)
    delta = np.abs(via_rgb - via_yuv) * std
    assert delta.max() <= 0.03, delta.max()


def test_native_shim_decodes_exact_planes():
    if not native.available():
        pytest.skip("native jpegyuv shim unavailable (no toolchain/libjpeg)")
    payload = photo_jpeg()
    res = native.decode_yuv420(payload, 256)
    assert res is not None
    y, u, v = res
    assert y.shape == (256, 256) and u.shape == (128, 128) and v.shape == (128, 128)
    # The shim ships the JPEG's stored planes; the PIL fallback re-derives
    # them from decoded RGB — equal to within decode rounding.
    rgb = preproc.decode_image(payload, "image/jpeg", edge=256)
    fy, fu, fv = preproc.rgb_to_yuv420(rgb)
    assert np.abs(y.astype(int) - fy.astype(int)).mean() < 3.0
    assert np.abs(u.astype(int) - fu.astype(int)).mean() < 3.0
    assert np.abs(v.astype(int) - fv.astype(int)).mean() < 3.0


def test_yuv_fallback_on_png():
    """Non-JPEG inputs still honor the YUV wire contract via the PIL path."""
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", (64, 64), (200, 30, 60)).save(buf, format="PNG")
    y, u, v = preproc.decode_image_yuv420(buf.getvalue(), "image/png", 256)
    assert y.shape == (256, 256) and u.shape == (128, 128)


def test_yuv_fallback_on_size_mismatch():
    """A JPEG at the wrong size falls back to PIL resize + re-subsample."""
    payload = photo_jpeg(edge=100)
    y, u, v = preproc.decode_image_yuv420(payload, "image/jpeg", 256)
    assert y.shape == (256, 256)


def test_rgb_to_yuv420_roundtrip_gray():
    """Flat gray image: Y == gray level, chroma == 128 (BT.601 identity)."""
    rgb = np.full((32, 32, 3), 128, np.uint8)
    y, u, v = preproc.rgb_to_yuv420(rgb)
    assert np.all(y == 128) and np.all(u == 128) and np.all(v == 128)


def test_decode_npy_items_single_vs_batch():
    """One parse decides single vs client batch; over-limit rejects."""

    def npy(arr):
        buf = io.BytesIO()
        np.save(buf, arr)
        return buf.getvalue()

    one = np.random.default_rng(0).integers(0, 255, (16, 16, 3), dtype=np.uint8)
    items, batched = preproc.decode_npy_items(npy(one), 16, max_items=8)
    assert not batched and len(items) == 1
    np.testing.assert_array_equal(items[0], one)

    batch = np.stack([one, one + 1])
    items, batched = preproc.decode_npy_items(npy(batch), 16, max_items=8)
    assert batched and len(items) == 2
    # resize path: wire edge differs
    items, _ = preproc.decode_npy_items(npy(batch), 8, max_items=8)
    assert items[0].shape == (8, 8, 3)

    with pytest.raises(ValueError, match="limit"):
        preproc.decode_npy_items(npy(np.zeros((9, 4, 4, 3), np.uint8)), 4, max_items=8)
